"""Self-healing multi-process training (ISSUE 15): rank-failure
supervisor, collective hang watchdog, bounded elastic restart.

Fast legs (tier-1, the ci/fault_gate.sh set): the fault-injection
registry's new points, the hang watchdog's trip/exemption/heartbeat
semantics, the supervisor state machine over REAL (stdlib, jax-free)
child processes — rank crash → shrink → resume, crash-loop bound with
exactly one ``crash_loop`` dump and zero orphans/stale heartbeats,
heartbeat-staleness detection, hang-exit classification — plus the
shrink-policy/elasticity solvers, the rendezvous retry helper, the new
watchdog rules' latch semantics, config validation, and the viewer's
fault timeline.

Slow legs (the acceptance criteria, over 2 real engine processes):
SIGKILL of rank 1 mid-training auto-recovers to a smaller valid world
from the latest snapshot with the loss trajectory preserved
step-for-step and exactly one latched ``rank_dead`` dump; an injected
in-collective hang is detected within ``hang_deadline_s`` + grace and
restarted (no eternal hang).
"""

import glob
import json
import os
import signal
import sys
import textwrap
import time

import pytest

from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.runtime.elastic.hang import (EXIT_HANG, HangWatchdog,
                                                heartbeat_path)
from deepspeed_tpu.runtime.elastic.supervisor import (
    EXIT_CRASH_LOOP, Supervisor, solve_next_world,
    valid_worlds_from_elasticity)
from deepspeed_tpu.telemetry.anomaly import Watchdog
from deepspeed_tpu.telemetry.recorder import FlightRecorder

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _dumps(d, rule=None):
    out = sorted(glob.glob(os.path.join(d, "flight_*.jsonl")))
    if rule is not None:
        out = [p for p in out if rule in os.path.basename(p)]
    return out


# ------------------------------------------------ fault injection registry


def test_fault_injection_new_points(monkeypatch):
    """sigkill_at_step delivers SIGKILL exactly at its step through the
    real step_end point; exit_at_step hard-exits; hang_in_collective
    sleeps only at its step at collective_enter; crash_during_delivery
    raises at serving_deliver with rid filtering."""
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append(
        (pid, sig)))
    with faults.sigkill_at_step(3):
        faults.fire("step_end", step=2)
        assert kills == []
        faults.fire("step_end", step=3)
        faults.fire("step_end", step=3)          # once only
    assert kills == [(os.getpid(), signal.SIGKILL)]

    exits = []
    monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
    with faults.exit_at_step(1, code=7):
        faults.fire("step_end", step=0)
        faults.fire("step_end", step=1)
    assert exits == [7]

    slept = []
    monkeypatch.setattr(time, "sleep", lambda s: slept.append(s))
    with faults.hang_in_collective(2, hang_s=123.0):
        faults.fire("collective_enter", step=1)
        assert slept == []
        faults.fire("collective_enter", step=2)
        faults.fire("collective_enter", step=2)  # once only
    assert slept == [123.0]

    with faults.crash_during_delivery(match_rid=5, times=1):
        faults.fire("serving_deliver", rid=4)    # filtered out
        with pytest.raises(faults.SimulatedCrash):
            faults.fire("serving_deliver", rid=5)
        faults.fire("serving_deliver", rid=5)    # budget spent
    faults.fire("serving_deliver", rid=5)        # unregistered


# --------------------------------------------------------- hang watchdog


def test_hang_watchdog_trips_with_one_rank_dead_dump(tmp_path):
    """A dispatch blocked past the deadline becomes: one rank_hang ring
    event, one LATCHED rank_dead dump carrying the ring, heartbeat
    removed, and the distinct EXIT_HANG code through exit_fn."""
    rec = FlightRecorder()
    rec.record("step", step=7)                   # pre-hang ring history
    dump_dir = str(tmp_path / "dumps")
    wd = Watchdog(dump_dir, recorder=rec, source="train")
    hb_dir = str(tmp_path / "hb")
    exits = []
    hw = HangWatchdog(0.3, poll_s=0.05, rank=0, world=2, watchdog=wd,
                      recorder=rec, heartbeat_dir=hb_dir,
                      heartbeat_interval_s=0.05, restart_epoch=2,
                      exit_fn=exits.append)
    assert os.path.exists(heartbeat_path(hb_dir, 0))
    hw.enter_dispatch("step", 0)                 # first: compile-exempt
    time.sleep(0.6)
    assert exits == [] and hw.tripped is None
    hw.exit_dispatch()
    hw.enter_dispatch("step", 1)
    t0 = time.time()
    while not exits and time.time() - t0 < 5:
        time.sleep(0.02)
    assert exits == [EXIT_HANG]
    assert hw.tripped["step"] == 1 and hw.tripped["blocked_s"] > 0.3
    # the latched rank_dead dump, exactly one, with the pre-hang ring
    dumps = _dumps(dump_dir, "rank_dead")
    assert len(dumps) == 1
    lines = [json.loads(x) for x in open(dumps[0])]
    assert lines[0]["rule"] == "rank_dead"
    assert lines[0]["detail"]["reason"] == "collective_hang"
    assert lines[0]["detail"]["restart_epoch"] == 2
    assert any(ev.get("kind") == "step" and ev.get("step") == 7
               for ev in lines[1:])
    assert any(ev.get("kind") == "rank_hang" for ev in rec.events())
    # heartbeat removed at trip: the supervisor cannot mistake the
    # exit window for a live rank
    assert not os.path.exists(heartbeat_path(hb_dir, 0))


def test_hang_watchdog_first_region_slack_per_kind(tmp_path):
    """The compile allowance is per KIND and is SLACK, not exemption:
    the first step dispatch and the first boundary exchange each
    tolerate factor× the deadline (both compile), the second
    occurrence of either is held to the plain deadline — and a first
    occurrence blocked past factor× the deadline still trips (a peer
    dead before this rank's first boundary must be caught)."""
    exits = []
    hw = HangWatchdog(0.2, poll_s=0.05, exit_fn=exits.append,
                      first_deadline_factor=10.0)
    for kind in ("step", "exchange"):
        hw.enter_dispatch(kind, 0)
        time.sleep(0.45)                 # past deadline, inside 10x
        assert exits == [], kind
        hw.exit_dispatch()
    hw.enter_dispatch("exchange", 1)
    t0 = time.time()
    while not exits and time.time() - t0 < 5:
        time.sleep(0.02)
    assert exits == [EXIT_HANG]
    assert hw.tripped["kind"] == "exchange"
    assert hw.tripped["deadline_s"] == pytest.approx(0.2)

    # never-exempt: a FIRST occurrence blocked past factor x deadline
    # trips too (with the applied 3x limit in the detail)
    exits2 = []
    hw2 = HangWatchdog(0.1, poll_s=0.03, exit_fn=exits2.append,
                       first_deadline_factor=3.0)
    hw2.enter_dispatch("exchange", 0)    # occurrence 1
    t0 = time.time()
    while not exits2 and time.time() - t0 < 5:
        time.sleep(0.02)
    assert exits2 == [EXIT_HANG]
    assert hw2.tripped["deadline_s"] == pytest.approx(0.3)
    assert hw2.tripped["blocked_s"] > 0.3


def test_heartbeat_keeps_beating_and_stop_cleans_up(tmp_path):
    hb_dir = str(tmp_path)
    hw = HangWatchdog(60.0, poll_s=0.03, rank=3, heartbeat_dir=hb_dir,
                      heartbeat_interval_s=0.05)
    path = heartbeat_path(hb_dir, 3)
    m0 = os.path.getmtime(path)
    t0 = time.time()
    while os.path.getmtime(path) == m0 and time.time() - t0 < 5:
        time.sleep(0.02)
    assert os.path.getmtime(path) > m0           # it beats
    hw.stop()
    assert not os.path.exists(path)              # and cleans up


# ------------------------------------------------- watchdog rule latches


def test_rank_dead_latches_and_world_ok_rearms(tmp_path):
    wd = Watchdog(str(tmp_path), recorder=FlightRecorder())
    assert wd.note_rank_dead(rank=1, reason="signal:9") is not None
    assert wd.note_rank_dead(rank=0, reason="exit:1") is None  # latched
    wd.note_world_ok()
    assert wd.note_rank_dead(rank=1, reason="signal:9") is not None
    assert wd.trips["rank_dead"] == 2


def test_crash_loop_latches_terminally(tmp_path):
    wd = Watchdog(str(tmp_path), recorder=FlightRecorder())
    assert wd.note_crash_loop(restarts=3, max_restarts=3) is not None
    assert wd.note_crash_loop(restarts=3, max_restarts=3) is None
    wd.note_world_ok()                           # does NOT re-arm it
    assert wd.note_crash_loop(restarts=3, max_restarts=3) is None
    assert len(_dumps(str(tmp_path), "crash_loop")) == 1


# ------------------------------------------------------- shrink policy


def test_solve_next_world_policy():
    # unconstrained: arithmetic shrink, floored, in-place retry at min
    assert solve_next_world(8, 1) == 7
    assert solve_next_world(8, 3) == 5
    assert solve_next_world(1, 1) == 1
    assert solve_next_world(2, 5, min_world=1) == 1
    # HCN-constrained: largest valid world <= survivors
    assert solve_next_world(8, 1, valid_worlds=[1, 2, 4, 8]) == 4
    assert solve_next_world(4, 1, valid_worlds=[1, 2, 4, 8]) == 2
    # nothing fits the shrunk target -> in-place retry at largest
    # valid size the current world could run
    assert solve_next_world(2, 2, valid_worlds=[2]) == 2
    # nothing >= min_world at all -> terminal
    assert solve_next_world(2, 1, valid_worlds=[4, 8]) is None
    assert solve_next_world(4, 1, valid_worlds=[1, 2],
                            min_world=3) is None


def test_valid_worlds_from_elasticity():
    ecfg = {"elasticity": {"enabled": True, "max_train_batch_size": 24,
                           "micro_batch_sizes": [1, 2, 4],
                           "min_chips": 1, "max_chips": 16,
                           "version": 0.1}}
    # chips {1,2,3,4,6,8,12} / 4 local devices -> process worlds 1,2,3
    assert valid_worlds_from_elasticity(ecfg, local_devices=4) \
        == [1, 2, 3]
    assert 8 in valid_worlds_from_elasticity(ecfg, local_devices=1)
    assert valid_worlds_from_elasticity({}, local_devices=1) is None


# ---------------------------------------------------- rendezvous retry


def test_rendezvous_retry_backoff_and_giveup():
    from deepspeed_tpu.utils.distributed import (_rendezvous_retry_env,
                                                 _retry_rendezvous)
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise RuntimeError("UNAVAILABLE: failed to connect to "
                               "coordinator")
        return "up"

    assert _retry_rendezvous(flaky, retries=8, backoff_s=0.25,
                             sleep=sleeps.append,
                             rng=lambda: 0.0) == "up"
    assert len(calls) == 4
    assert sleeps == [0.25, 0.5, 1.0]            # exponential, jitter=0

    # non-connection errors never retry
    def config_error():
        calls.append(1)
        raise ValueError("num_processes mismatch: 3 != 2")
    calls.clear()
    with pytest.raises(ValueError):
        _retry_rendezvous(config_error, retries=8, backoff_s=0.01,
                          sleep=sleeps.append)
    assert len(calls) == 1

    # budget exhaustion re-raises the last connection error
    def always_down():
        raise OSError("connection refused")
    with pytest.raises(OSError):
        _retry_rendezvous(always_down, retries=2, backoff_s=0.0,
                          sleep=lambda s: None)

    # env contract (what the supervisor exports)
    assert _rendezvous_retry_env({}) == (8, 0.5)
    assert _rendezvous_retry_env(
        {"DSTPU_RENDEZVOUS_RETRIES": "3",
         "DSTPU_RENDEZVOUS_BACKOFF_S": "1.5"}) == (3, 1.5)
    assert _rendezvous_retry_env(
        {"DSTPU_RENDEZVOUS_RETRIES": "garbage"}) == (8, 0.5)


# -------------------------------------------------- config validation


def test_fault_tolerance_config_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    base = {"train_batch_size": 8}
    cfg = DeepSpeedConfig(dict(base), world_size=1)
    assert not cfg.fault_tolerance_config.enabled     # absent block
    good = dict(base, fault_tolerance={"hang_deadline_s": 15.0,
                                       "rendezvous_retries": 2})
    ftc = DeepSpeedConfig(good, world_size=1).fault_tolerance_config
    assert ftc.enabled and ftc.hang_deadline_s == 15.0
    assert ftc.rendezvous_retries == 2
    for bad in ({"hang_deadline_s": 0},
                {"hang_poll_s": -1},
                {"heartbeat_interval_s": 0},
                {"rendezvous_retries": -1},
                {"rendezvous_backoff_s": 0}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(dict(base, fault_tolerance=bad),
                            world_size=1)
    off = dict(base, fault_tolerance={"enabled": False,
                                      "hang_deadline_s": 0})
    assert not DeepSpeedConfig(
        off, world_size=1).fault_tolerance_config.enabled


# --------------------------------------- supervisor over real processes
# stdlib workers: the state machine is exercised over REAL child
# processes (spawn, kill, reap) without paying a jax import per child.


def _write_worker(tmp_path, body):
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(body))
    return str(path)


def _mk_sup(script, world, tmp_path, **kw):
    kw.setdefault("grace_kill_s", 2.0)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_max_s", 0.1)
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("dump_dir", str(tmp_path / "sup_dumps"))
    return Supervisor([sys.executable, script], world,
                      heartbeat_dir=str(tmp_path / "hb"),
                      recorder=FlightRecorder(), **kw)


def test_supervisor_rank_crash_shrinks_and_resumes(tmp_path):
    """Rank 1 of 2 crashes: the survivor is torn down, the world
    restarts at 1 with the epoch stamped into the child env, exactly
    one rank_dead dump is written, and success leaves no stale
    heartbeat files."""
    script = _write_worker(tmp_path, """
        import os, sys, time
        rank = int(os.environ["DSTPU_PROCESS_ID"])
        epoch = int(os.environ["DSTPU_RESTART_EPOCH"])
        world = int(os.environ["DSTPU_NUM_PROCESSES"])
        print(f"UP rank={rank} epoch={epoch} world={world}", flush=True)
        if epoch == 0 and rank == 1:
            time.sleep(0.2); sys.exit(3)
        if epoch >= 1:
            sys.exit(0)
        time.sleep(60)
    """)
    sup = _mk_sup(script, 2, tmp_path)
    assert sup.run(deadline_s=60) == 0
    assert sup.restarts == 1
    assert sup.incidents[0]["reasons"][1] == "exit:3"
    assert sup.incidents[0]["lost"] == 1
    assert len(_dumps(sup.watchdog.dump_dir, "rank_dead")) == 1
    # the restarted epoch saw the shrunk world + bumped epoch
    assert "epoch=1 world=1" in open(sup.log_paths[(1, 0)]).read()
    # clean end state: no orphans, no stale heartbeats
    assert all(p.poll() is not None for p in sup.procs.values())
    assert not glob.glob(os.path.join(str(tmp_path / "hb"), "hb_*"))
    kinds = [e["kind"] for e in sup.recorder.events()]
    for k in ("supervisor_spawn", "rank_exit", "world_down", "restart"):
        assert k in kinds, kinds


def test_supervisor_crash_loop_bounded(tmp_path):
    """The ISSUE 15 satellite: a rank that dies every restart exhausts
    max_restarts — the supervisor exits nonzero with exactly one
    crash_loop dump, and no orphan children or stale heartbeat files
    remain."""
    script = _write_worker(tmp_path, """
        import sys, time
        time.sleep(0.05)
        sys.exit(7)
    """)
    sup = _mk_sup(script, 2, tmp_path, max_restarts=2)
    rc = sup.run(deadline_s=60)
    assert rc == EXIT_CRASH_LOOP and rc != 0
    assert sup.restarts == 2                     # the full budget
    assert len(_dumps(sup.watchdog.dump_dir, "crash_loop")) == 1
    assert all(p.poll() is not None for p in sup.procs.values())
    assert not glob.glob(os.path.join(str(tmp_path / "hb"), "hb_*"))
    kinds = [e["kind"] for e in sup.recorder.events()]
    assert kinds.count("crash_loop") == 1
    # every epoch is visible on the timeline: 3 spawns, 2 restarts
    assert kinds.count("supervisor_spawn") == 3
    assert kinds.count("restart") == 2


def test_supervisor_detects_stale_heartbeat(tmp_path):
    """A process frozen without exiting (it beats once, then stops)
    is detected through heartbeat staleness and restarted."""
    script = _write_worker(tmp_path, """
        import os, sys, time
        rank = int(os.environ["DSTPU_PROCESS_ID"])
        epoch = int(os.environ["DSTPU_RESTART_EPOCH"])
        if epoch >= 1:
            sys.exit(0)
        hb = os.path.join(os.environ["DSTPU_HEARTBEAT_DIR"],
                          f"hb_rank{rank}")
        open(hb, "w").write("beat once\\n")
        time.sleep(60)                           # frozen: never beats again
    """)
    sup = _mk_sup(script, 1, tmp_path, heartbeat_stale_s=0.6)
    assert sup.run(deadline_s=60) == 0
    assert sup.restarts == 1
    assert sup.incidents[0]["reasons"][0].startswith("heartbeat_stale")


def test_supervisor_classifies_hang_exit(tmp_path):
    """A rank exiting EXIT_HANG is a healthy DETECTOR: the casualty
    count stays at the (unknown, floor-1) stuck peer, and teardown must
    SIGKILL a survivor that swallows SIGTERM — exactly what a rank
    parked in a dead collective or a PEP 475-retried sleep does."""
    script = _write_worker(tmp_path, f"""
        import os, signal, sys, time
        rank = int(os.environ["DSTPU_PROCESS_ID"])
        epoch = int(os.environ["DSTPU_RESTART_EPOCH"])
        if epoch >= 1:
            sys.exit(0)
        if rank == 0:
            time.sleep(0.3)
            os._exit({EXIT_HANG})                # the hang detector
        signal.signal(signal.SIGTERM, lambda *a: None)   # swallower
        time.sleep(60)                           # the stuck peer
    """)
    sup = _mk_sup(script, 2, tmp_path, grace_kill_s=0.5)
    assert sup.run(deadline_s=60) == 0
    assert sup.incidents[0]["reasons"][0] == "hang_detected"
    assert sup.incidents[0]["lost"] == 1         # the stuck peer, not 2
    assert sup.world == 1


# ------------------------------------------------------- view timeline


def test_view_renders_fault_timeline_synthetic(tmp_path):
    """The die → detect → shrink → resume timeline from the supervisor
    + worker event kinds, no jax, no engine."""
    from deepspeed_tpu.telemetry import view
    evs = [
        {"kind": "supervisor_spawn", "ts": 1.0, "seq": 1, "world": 2,
         "restart_epoch": 0, "port": 1234},
        {"kind": "rank_exit", "ts": 2.0, "seq": 2, "rank": 1,
         "exit_code": -9, "reason": "signal:9", "restart_epoch": 0},
        {"kind": "rank_hang", "ts": 2.5, "seq": 3, "rank": 0,
         "region": "step", "blocked_s": 6.2, "deadline_s": 6.0},
        {"kind": "world_down", "ts": 3.0, "seq": 4, "restart_epoch": 0,
         "survivors_torn_down": 1, "lost": 1},
        {"kind": "restart", "ts": 4.0, "seq": 5, "restart_epoch": 1,
         "world_from": 2, "world_to": 1, "backoff_s": 0.7,
         "restarts": 1, "reason": "signal:9"},
        {"kind": "restart_epoch", "ts": 5.0, "seq": 6, "epoch": 1,
         "world": 1},
        {"kind": "resume", "ts": 6.0, "seq": 7, "step": 2,
         "tag": "global_step2", "from_dp": 8, "to_dp": 4, "micro": 2,
         "grad_accum": 3, "fell_back": 0},
        {"kind": "crash_loop", "ts": 7.0, "seq": 8, "restarts": 3,
         "max_restarts": 3, "last_reason": "exit:7"},
    ]
    path = tmp_path / "d.jsonl"
    path.write_text("\n".join(json.dumps(e) for e in evs) + "\n")
    out = "\n".join(view.render(str(path)))
    assert "checkpoint / restore / preempt timeline" in out
    for needle in ("supervisor_spawn", "rank 1 down: signal:9",
                   "blocked 6.2s in step", "world 2→1",
                   "worker up in epoch 1", "dp 8→4",
                   "3 restart(s) spent"):
        assert needle in out, (needle, out)


# --------------------------------------------- slow: 2-process acceptance

_TRAIN_WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed
    init_distributed()

    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.runtime.elastic import faults
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel

    snap_dir, dump_dir, total, fault = sys.argv[1:5]
    total = int(total)
    rank = jax.process_index()
    epoch = int(os.environ.get("DSTPU_RESTART_EPOCH", "0"))
    ndev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=ndev))
    cfg = {
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        # the PR-7 HCN ladder recipe: batch 24 factors for dp 2 (micro
        # 4, gas 3) and dp 1 (micro 4, gas 6) — the shrink re-solves
        # BOTH micro partitioning and accumulation depth
        "elasticity": {"enabled": True, "max_train_batch_size": 24,
                       "micro_batch_sizes": [1, 2, 4], "min_chips": 1,
                       "max_chips": 16, "version": 0.1},
        "snapshot": {"path": snap_dir, "interval_steps": 1,
                     "grace_secs": 20.0},
        "fault_tolerance": {"hang_deadline_s": 8.0,
                            "heartbeat_interval_s": 0.2},
        "monitor": {"enabled": False,
                    "watchdog": {"dump_dir": dump_dir,
                                 "step_time_factor": 1000.0,
                                 "swap_stall_factor": 1000.0,
                                 "ckpt_stall_factor": 1000.0,
                                 "check_nan": False}},
    }
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    rs = np.random.RandomState(0)
    batch = (rs.randn(24, 8).astype(np.float32),
             rs.randint(0, 4, (24,)).astype(np.int32))
    _fault_cm = None                  # keep the CM alive: a dropped
    if epoch == 0 and rank == 1:      # reference GC-closes the
        if fault == "sigkill":        # generator and UNREGISTERS it
            _fault_cm = faults.sigkill_at_step(3)
        elif fault == "hang":
            _fault_cm = faults.hang_in_collective(3, hang_s=600.0)
        if _fault_cm is not None:
            _fault_cm.__enter__()
    losses = {}
    while engine.global_steps < total:
        loss = float(engine.train_batch(batch))
        losses[engine.global_steps] = loss
    print("TRAJ", rank, epoch,
          json.dumps({str(k): v for k, v in losses.items()}), flush=True)
""")


def _reference_trajectory(total):
    """The uninterrupted dp=2 run in THIS process (2 of the virtual
    devices — the same dp the supervised world starts at): elasticity
    preserves the effective batch across world sizes, so the
    supervised run's post-restart dp=1 losses must match these
    step-for-step."""
    import jax
    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import MeshConfig, make_mesh
    from tests.simple_model import SimpleModel
    cfg = {
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "elasticity": {"enabled": True, "max_train_batch_size": 24,
                       "micro_batch_sizes": [1, 2, 4], "min_chips": 1,
                       "max_chips": 16, "version": 0.1},
    }
    engine, _, _, _ = dstpu.initialize(
        config=cfg, model=SimpleModel(),
        mesh=make_mesh(MeshConfig(data=2), devices=jax.devices()[:2]))
    rs = np.random.RandomState(0)
    batch = (rs.randn(24, 8).astype(np.float32),
             rs.randint(0, 4, (24,)).astype(np.int32))
    return {s + 1: float(engine.train_batch(batch))
            for s in range(total)}


def _run_supervised(tmp_path, fault, total=6, deadline_s=480):
    script = tmp_path / "train_worker.py"
    script.write_text(_TRAIN_WORKER)
    snap = str(tmp_path / "snaps")
    wdump = str(tmp_path / "worker_dumps")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO_ROOT + os.pathsep
                + os.environ.get("PYTHONPATH", "")})
    sup = Supervisor(
        [sys.executable, str(script), snap, wdump, str(total), fault],
        2, heartbeat_dir=str(tmp_path / "hb"),
        dump_dir=str(tmp_path / "sup_dumps"),
        valid_worlds=valid_worlds_from_elasticity(
            {"elasticity": {"enabled": True, "max_train_batch_size": 24,
                            "micro_batch_sizes": [1, 2, 4],
                            "min_chips": 1, "max_chips": 16,
                            "version": 0.1}}, local_devices=1),
        hang_deadline_s=8.0, grace_kill_s=3.0, max_restarts=2,
        backoff_base_s=0.2, backoff_max_s=0.5, poll_s=0.1,
        local_devices=1, env=env, cwd=REPO_ROOT,
        recorder=FlightRecorder())
    rc = sup.run(deadline_s=deadline_s)
    return sup, rc, wdump


def _traj_from_log(path):
    import re
    text = open(path).read()
    m = re.search(r"TRAJ (\d+) (\d+) (\{.*\})", text)
    assert m, text
    return {int(k): v for k, v in json.loads(m.group(3)).items()}


@pytest.mark.slow
def test_sigkill_rank1_auto_recovers_two_processes(tmp_path):
    """THE acceptance leg: SIGKILL of rank 1 mid-training (2 real
    processes, dp2, ZeRO-2). The supervisor detects the death, tears
    down the survivor, restarts at the HCN-valid shrunk world (1
    process, dp1 — micro stays 4, gas re-solves 3 → 6, effective batch
    24 preserved), auto-resumes from the latest committed snapshot,
    and the post-restart loss trajectory matches the uninterrupted
    dp2 run step-for-step. Exactly one latched rank_dead dump.

    (One device per process on purpose: multi-device-per-process GSPMD
    programs over the gloo transport nondeterministically interleave
    their independent psums on one TCP pair — a pre-existing backend
    bug this PR documents in ROADMAP.md, reproducible on the seed
    tree without any fault-tolerance code.)"""
    import numpy as np
    total = 6
    sup, rc, wdump = _run_supervised(tmp_path, "sigkill", total=total)
    assert rc == 0
    assert sup.restarts == 1
    assert sup.incidents[0]["reasons"][1] == "signal:9"
    # shrink: 2 procs (dp2) -> 1 proc (dp1), the HCN-valid world
    assert sup.world == 1
    # exactly ONE latched rank_dead dump (the supervisor's); the
    # workers were torn down before their own deadline could dump
    assert len(_dumps(sup.watchdog.dump_dir, "rank_dead")) == 1
    assert _dumps(wdump, "rank_dead") == []
    # resumed from the last committed snapshot (global_step2): the
    # restarted epoch's first completed step is 3
    traj = _traj_from_log(sup.log_paths[(1, 0)])
    assert min(traj) == 3 and max(traj) == total
    # loss trajectory preserved step-for-step vs the uninterrupted run
    ref = _reference_trajectory(total)
    for s in sorted(traj):
        np.testing.assert_allclose(traj[s], ref[s], rtol=2e-5)
    # no orphans, no stale heartbeats
    assert all(p.poll() is not None for p in sup.procs.values())
    assert not glob.glob(os.path.join(str(tmp_path / "hb"), "hb_*"))


@pytest.mark.slow
def test_hang_in_collective_detected_and_restarted(tmp_path):
    """The hang acceptance leg: rank 1 parks inside the boundary
    exchange (sleep at collective_enter), so rank 0 blocks inside the
    step dispatch with NO process death. Rank 0's hang watchdog
    converts the stall into one rank_dead dump + EXIT_HANG within
    hang_deadline_s + grace; the supervisor classifies the exit,
    SIGKILLs the sleeper, restarts the shrunk world, and training
    completes — no eternal hang."""
    total = 6
    sup, rc, wdump = _run_supervised(tmp_path, "hang", total=total)
    assert rc == 0
    assert sup.restarts == 1
    # rank 0 exited with the distinct hang code
    assert sup.incidents[0]["reasons"][0] == "hang_detected"
    assert sup.incidents[0]["lost"] == 1
    # the WORKER-side latched rank_dead dump names the blocked region
    # and stays within deadline + grace
    dumps = _dumps(wdump, "rank_dead")
    assert len(dumps) == 1
    header = json.loads(open(dumps[0]).readline())
    det = header["detail"]
    assert det["reason"] == "collective_hang"
    assert 8.0 < det["blocked_s"] < 8.0 + 6.0
    # the restarted epoch completed the run from the last committed
    # snapshot
    traj = _traj_from_log(sup.log_paths[(1, 0)])
    assert max(traj) == total and min(traj) == 3
    assert all(p.poll() is not None for p in sup.procs.values())
