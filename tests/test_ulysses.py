"""Ulysses (all-to-all) sequence parallelism tests — exactness vs dense
attention and gradient parity, on the virtual CPU mesh (the ring-attention
test methodology)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel.ulysses import ulysses_attention
from deepspeed_tpu.ops.attention import reference_attention


def _mesh(seq):
    devs = jax.devices()
    if len(devs) < seq:
        pytest.skip(f"need {seq} devices")
    return mesh_lib.make_mesh(mesh_lib.MeshConfig(data=1, seq=seq),
                              devices=devs[:seq])


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = _mesh(4)
    B, H, S, D = 2, 8, 64, 16
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3))
    got = ulysses_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_gradients_match():
    mesh = _mesh(4)
    B, H, S, D = 1, 4, 32, 8
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
               for _ in range(3))

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_ulysses_single_device_passthrough():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=1),
                              devices=jax.devices()[:1])
    q = jnp.ones((1, 2, 16, 8))
    out = ulysses_attention(q, q, q, mesh, causal=True)
    assert out.shape == q.shape


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh(4)
    q = jnp.ones((1, 6, 32, 8))   # 6 heads not divisible by 4
    with pytest.raises(AssertionError):
        ulysses_attention(q, q, q, mesh, causal=False)


@pytest.mark.slow
def test_gpt2_trains_with_ulysses_sp():
    """End-to-end: GPT-2 with sp_backend='ulysses' trains on a seq-sharded
    mesh and matches the single-device trajectory."""
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel

    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    batch = {"input_ids": np.random.RandomState(0)
             .randint(0, 512, (4, 64)).astype(np.int32)}

    def run(mesh_cfg, n, sp):
        mesh = mesh_lib.make_mesh(mesh_cfg, devices=jax.devices()[:n])
        cfg = {"train_batch_size": 4,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "seed": 5}
        model = GPT2LMHeadModel(gpt2_tiny(n_head=4, sp_backend=sp))
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model,
                                           mesh=mesh)
        return [float(engine.train_batch(batch)) for _ in range(5)]

    base = run(mesh_lib.MeshConfig(data=1), 1, "ulysses")
    got = run(mesh_lib.MeshConfig(data=1, seq=4), 4, "ulysses")
    np.testing.assert_allclose(got[0], base[0], rtol=1e-4)
    np.testing.assert_allclose(got, base, rtol=2e-2, atol=2e-2)
