import time
import numpy as np
import jax, jax.numpy as jnp

# h2d upload cost through tunnel
b = np.random.randint(0, 50304, (8, 1024)).astype(np.int32)
for _ in range(2):
    x = jnp.asarray(b); jax.block_until_ready(x)
t0 = time.perf_counter()
for _ in range(10):
    x = jnp.asarray(b); jax.block_until_ready(x)
print(f"h2d 32KB: {(time.perf_counter()-t0)/10*1000:.1f}ms", flush=True)

# rng split cost
key = jax.random.PRNGKey(0)
for _ in range(2):
    key, s = jax.random.split(key)
jax.block_until_ready(key)
t0 = time.perf_counter()
for _ in range(10):
    key, s = jax.random.split(key)
jax.block_until_ready(key)
print(f"rng split: {(time.perf_counter()-t0)/10*1000:.1f}ms", flush=True)

from deepspeed_tpu.utils.timer import ThroughputTimer
tt = ThroughputTimer(batch_size=8)
t0 = time.perf_counter()
for _ in range(10):
    tt.start(); tt.stop()
print(f"tput timer: {(time.perf_counter()-t0)/10*1000:.1f}ms", flush=True)
