"""GPT-2 1.5B (gpt2_xl, the BASELINE.md north-star config) on ONE 16 GB
chip via ZeRO-Offload — the max-params-per-chip evidence run, and the
gpt2_xl entry bench.py embeds (it runs this script as a bounded
subprocess).

The 48-layer offload program takes ~40 min to compile through the
tunneled backend — a persistent XLA compilation cache (.jax_cache) makes
re-runs on the same machine compile-free. The steady-state step is
dominated by the host optimizer: this harness host has a single CPU core
behind the tunnel (measured ~405 s/step with the pipelined d2h/SIMD/h2d
streamed step, loss falling 11.16 → 10.49 over 4 steps; a real TPU-VM
host with its usual core count and PCIe runs the same host step in
seconds). MFU is reported honestly against the chip peak — on this
harness it measures the 1-core host, not the architecture.

Prints one JSON line: params, fit evidence, samples/sec, honest MFU.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3,
                    help="steady-state steps to time after the first")
    args = ap.parse_args(argv)

    import jax
    from bench import _enable_compile_cache, peak_flops, model_flops_per_token
    _enable_compile_cache()
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_xl, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    cfg_m = gpt2_xl(dtype=jnp.bfloat16, scan_layers=True, remat=True,
                    remat_policy="projs", loss_chunk=1024)
    cfg = {
        "train_batch_size": 4,
        "zero_optimization": {"stage": 3, "overlap_comm": True,
                              "offload_optimizer": {"device": "cpu"}},
        "bf16": {"enabled": True},
        "data_types": {"grad_dtype": "bf16"},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000,
    }
    dev = jax.devices()[0]
    mesh = make_mesh(MeshConfig(data=1), devices=[dev])
    engine, _, _, _ = dstpu.initialize(config=cfg,
                                       model=GPT2LMHeadModel(cfg_m),
                                       mesh=mesh)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 50257, size=(4, 1024))
             .astype(np.int32)}
    losses = []
    t0 = time.perf_counter()
    losses.append(float(engine.train_batch(batch)))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(args.steps):
        losses.append(float(engine.train_batch(batch)))
    dt = (time.perf_counter() - t0) / args.steps

    tokens_per_step = 4 * 1024
    achieved = model_flops_per_token(cfg_m) * tokens_per_step / dt
    mfu = achieved / peak_flops(dev)
    print(json.dumps({
        "metric": "gpt2_xl_1p5b_zero_offload_params_per_chip",
        "value": round(cfg_m.num_params() / 1e9, 3),
        "unit": "B params on one 16GB chip",
        "detail": {"first_loss": losses[0], "last_loss": losses[-1],
                   "compile_s": round(compile_s, 1),
                   "steady_step_s": round(dt, 1),
                   "samples_per_sec": round(4 / dt, 4),
                   # honest: the step is host-SIMD-bound on this 1-core
                   # harness host; the number measures the host, not the
                   # TPU architecture (see module docstring)
                   "mfu_pct_on_this_harness": round(mfu * 100, 3)},
    }))
    # mark the compilation cache warm for bench.py's bounded subprocess
    try:
        from bench import XL_WARM_SENTINEL
        with open(XL_WARM_SENTINEL, "w") as f:
            f.write("ok")
    except OSError:
        pass


if __name__ == "__main__":
    sys.exit(main())
