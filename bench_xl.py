"""GPT-2 1.5B (gpt2_xl, the BASELINE.md north-star config) on ONE 16 GB
chip via ZeRO-Offload — the max-params-per-chip evidence run.

Not part of bench.py's driver path: the 48-layer offload program takes
~25 min to compile through the tunneled backend, and the steady-state step
is dominated by the host optimizer (on this harness the host has a single
CPU core and sits behind the tunnel; measured 425 s/step, loss falling
11.16 -> 10.49 over 4 steps on 2026-07-30. A real TPU-VM host with its
usual core count and PCIe runs the same host step in seconds).

Prints one JSON line: params, fit evidence, samples/sec.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_xl, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    cfg_m = gpt2_xl(dtype=jnp.bfloat16, scan_layers=True, remat=True,
                    remat_policy="projs", loss_chunk=1024)
    cfg = {
        "train_batch_size": 4,
        "zero_optimization": {"stage": 3,
                              "offload_optimizer": {"device": "cpu"}},
        "bf16": {"enabled": True},
        "data_types": {"grad_dtype": "bf16"},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000,
    }
    mesh = make_mesh(MeshConfig(data=1), devices=[jax.devices()[0]])
    engine, _, _, _ = dstpu.initialize(config=cfg,
                                       model=GPT2LMHeadModel(cfg_m),
                                       mesh=mesh)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 50257, size=(4, 1024))
             .astype(np.int32)}
    losses = []
    t0 = time.perf_counter()
    losses.append(float(engine.train_batch(batch)))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(3):
        losses.append(float(engine.train_batch(batch)))
    dt = (time.perf_counter() - t0) / 3
    print(json.dumps({
        "metric": "gpt2_xl_1p5b_zero_offload_params_per_chip",
        "value": round(cfg_m.num_params() / 1e9, 3),
        "unit": "B params on one 16GB chip",
        "detail": {"first_loss": losses[0], "last_loss": losses[-1],
                   "compile_s": round(compile_s, 1),
                   "steady_step_s": round(dt, 1),
                   "samples_per_sec": round(4 / dt, 4)},
    }))


if __name__ == "__main__":
    sys.exit(main())
