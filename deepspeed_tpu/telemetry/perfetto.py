"""Perfetto / Chrome trace-event export of flight-recorder dumps.

Usage::

    python -m deepspeed_tpu.telemetry.view --format perfetto \\
        flight_rank0e0_*.jsonl flight_rank1e0_*.jsonl --out trace.json

Turns N per-rank / per-epoch watchdog dumps (anomaly.py) into ONE
Chrome trace-event JSON (the format ``ui.perfetto.dev`` and
``chrome://tracing`` both load), so the cross-rank story the text
viewer prints as tables becomes a zoomable timeline:

- each dump file becomes a **process** row, pid = the rank parsed from
  the dump header's ``source`` (the xproc workers stamp
  ``rank{N}e{E}``) — so two epochs of the same rank share one row —
  with the header's provenance (host, git sha, restart epoch) in the
  process label;
- each engine/replica becomes a **thread** row inside its rank
  (``replica`` field of serving events);
- duration-bearing events (``span``, ``prefill``, ``tick``,
  ``transport_encode``, ...) become complete slices ("X"); every other
  event becomes an instant ("i") so nothing in the ring is invisible;
- prefill→decode handoffs become **flow arrows** ("s"/"f") stitched
  per ``trace_id`` from each ``handoff_out`` to the ``handoff_in``
  that absorbed it — the causal hop ACROSS process rows;
- span causality (ISSUE 19): every event's ``span_id`` /
  ``parent_span`` ride in its ``args``, and :func:`orphan_spans` is
  the merge-integrity check — in a complete dump set every
  ``parent_span`` resolves to some event's ``span_id``; an orphan
  means a rank's dump is missing from the merge.

Pure stdlib, like view.py — the exporter must run where the dumps
landed (laptop, CI artifact store) with no jax and no numpy;
tests/test_metric_names.py pins the import chain and
ci/telemetry_gate.sh round-trips a golden dump with BOTH poisoned.

Output is DETERMINISTIC for a fixed input (events sorted by host
timestamp then ring sequence, flow ids assigned in that order, keys
sorted by the writer) — the CI golden test diffs it byte-for-byte.
"""

import json
import re

from deepspeed_tpu.telemetry.view import load_dump

# kinds whose payload carries a host-measured duration: kind ->
# (duration field, slice name; None = use the event's ``tag``). The
# recorder stamps ``ts`` at record time — the END of the measured
# interval — so slices start at ts - dur.
DURATION_KINDS = {
    "span": ("dur_s", None),
    "prefill": ("prefill_s", "prefill"),
    "tick": ("tick_s", "tick"),
    "spec_round": ("tick_s", "spec_round"),
    "transport_encode": ("dur_s", "transport_encode"),
    "swap_drain": ("wait_s", "swap_drain"),
}

# category per kind family — Perfetto colors/filters by ``cat``
_CATS = (
    ("serving", ("admit", "prefill", "tick", "spec_round", "finish",
                 "pool_exhausted", "serving_abort")),
    ("handoff", ("handoff_out", "handoff_in", "transport_encode",
                 "router_route", "router_block")),
    ("elastic", ("serving_drain", "serving_snapshot", "serving_restore",
                 "serving_requeue", "replica_scale", "replica_kill",
                 "ckpt_begin", "ckpt_commit", "ckpt_abort",
                 "ckpt_corrupt", "preempt_signal", "preempt", "resume",
                 "restart", "restart_epoch", "rank_exit", "rank_hang",
                 "world_down", "supervisor_spawn", "crash_loop")),
    ("cluster", ("cluster_fence",)),
    ("anomaly", ("anomaly",)),
)
_CAT_BY_KIND = {k: cat for cat, kinds in _CATS for k in kinds}

_RANK_RE = re.compile(r"rank(\d+)")


def _pid_for(header, idx):
    """pid + human label for one dump file. Rank parsed from the
    header source wins (both epochs of rank 1 belong on ONE row);
    a rankless dump (a single-process run, a supervisor dump) gets a
    stable per-file pid offset far from real ranks."""
    source = (header or {}).get("source") or ""
    m = _RANK_RE.search(str(source))
    if m:
        pid = int(m.group(1))
        label = f"rank {pid}"
    else:
        pid = 1000 + idx
        label = str(source) or f"dump {idx}"
    prov = (header or {}).get("provenance") or {}
    bits = [label]
    if prov.get("hostname"):
        bits.append(str(prov["hostname"]))
    if prov.get("git_sha") and prov["git_sha"] != "unknown":
        bits.append(str(prov["git_sha"]))
    if (header or {}).get("restart_epoch"):
        bits.append(f"epoch {header['restart_epoch']}")
    return pid, " ".join(bits)


def _args_of(ev):
    """Everything but the envelope — span ids included, so clicking a
    slice in the Perfetto UI shows its causal identity."""
    return {k: v for k, v in ev.items()
            if k not in ("ts", "seq", "kind") and v is not None}


def orphan_spans(events):
    """Merge-integrity check (the ISSUE 19 acceptance gate): every
    ``parent_span`` in the merged event set must be some event's
    ``span_id``. Returns the offending events (kind, span_id,
    parent_span, rid) — EMPTY means the dump set tells one complete
    causal story per trace; an orphan means the parent's rank/epoch
    dump is missing from the merge (or a span was minted and never
    emitted — a code bug this check is designed to catch in CI)."""
    ids = {ev.get("span_id") for ev in events
           if ev.get("span_id") is not None}
    out = []
    for ev in events:
        parent = ev.get("parent_span")
        if parent is not None and parent not in ids:
            out.append({"kind": ev.get("kind"),
                        "span_id": ev.get("span_id"),
                        "parent_span": parent,
                        "rid": ev.get("rid")})
    return out


def export(paths):
    """N dump paths -> one Chrome trace-event document (a JSON-able
    dict). Events keep their per-file pid; duplicate ring overlap
    within one file is already impossible (a dump is one ring
    snapshot), and cross-file dedup is NOT wanted here — two ranks
    recording the same logical hop are two real rows."""
    files = []
    for idx, path in enumerate(paths):
        header, events, _skipped = load_dump(path)
        pid, label = _pid_for(header, idx)
        files.append((pid, label, events))

    ts_all = [ev["ts"] for _pid, _l, evs in files for ev in evs
              if ev.get("ts") is not None]
    t0 = min(ts_all) if ts_all else 0.0

    def us(ts):
        return round((ts - t0) * 1e6, 1)

    out = []
    threads = {}                       # (pid, tid) -> name
    proc_labels = {}                   # pid -> label (first file wins)
    for pid, label, _evs in files:
        proc_labels.setdefault(pid, label)

    flow_next = [1]
    pending_out = {}                   # trace_id -> [flow ids in order]
    named_tids = {}                    # (pid, replica str) -> tid
    named_next = {}                    # pid -> named replicas seen

    for pid, _label, events in files:
        for ev in sorted(events, key=lambda e: (e.get("ts") or 0.0,
                                                e.get("seq") or 0)):
            ts = ev.get("ts")
            if ts is None:
                continue
            kind = ev.get("kind", "?")
            rep = ev.get("replica")
            if rep is None:
                tid = 0
            else:
                try:
                    tid = int(rep)
                except (TypeError, ValueError):
                    # string replica ids ("prefill0") — stable per-pid
                    # tids in first-seen order, offset past the
                    # integer-id range
                    key = (pid, str(rep))
                    tid = named_tids.get(key)
                    if tid is None:
                        tid = 1000 + named_next.get(pid, 0)
                        named_tids[key] = tid
                        named_next[pid] = named_next.get(pid, 0) + 1
            threads.setdefault(
                (pid, tid),
                f"replica {rep}" if rep is not None else "main")
            cat = _CAT_BY_KIND.get(kind, "event")
            args = _args_of(ev)
            dur = DURATION_KINDS.get(kind)
            if dur is not None and ev.get(dur[0]) is not None:
                dur_s = float(ev[dur[0]])   # sync-ok: JSON dump field
                name = dur[1] or str(ev.get("tag", kind))
                out.append({"ph": "X", "name": name, "cat": cat,
                            "pid": pid, "tid": tid,
                            "ts": us(ts - dur_s),
                            "dur": round(dur_s * 1e6, 1),
                            "args": args})
            else:
                out.append({"ph": "i", "name": kind, "cat": cat,
                            "pid": pid, "tid": tid, "ts": us(ts),
                            "s": "t", "args": args})
            # the cross-process hop: one arrow per handoff, matched
            # oldest-first per trace (a requeued request hands off
            # more than once — each out pairs with the NEXT in)
            trace = ev.get("trace")
            if kind == "handoff_out" and trace is not None:
                fid = flow_next[0]
                flow_next[0] += 1
                pending_out.setdefault(trace, []).append(fid)
                out.append({"ph": "s", "name": "handoff", "cat":
                            "handoff", "id": fid, "pid": pid,
                            "tid": tid, "ts": us(ts)})
            elif kind == "handoff_in" and trace is not None:
                queue = pending_out.get(trace)
                if queue:
                    fid = queue.pop(0)
                    out.append({"ph": "f", "bp": "e", "name": "handoff",
                                "cat": "handoff", "id": fid, "pid": pid,
                                "tid": tid, "ts": us(ts)})

    meta = []
    for pid in sorted(proc_labels):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": proc_labels[pid]}})
    for pid, tid in sorted(threads):
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": threads[(pid, tid)]}})
    out.sort(key=lambda e: (e["ts"], e["pid"], e["tid"],
                            e["ph"], e["name"]))
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def dumps(doc):
    """Deterministic serialization (sorted keys, no float repr drift
    beyond round()) — what the CI golden test diffs and ``--format
    perfetto`` prints."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))
