"""Flight-recorder dump viewer.

Usage::

    python -m deepspeed_tpu.telemetry.view <dump.jsonl>

Renders a watchdog dump (anomaly.py) — or any JSONL stream of recorder
events — as:

- the trigger header (rule, dump id, detail);
- a per-step phase-attribution table: one row per training step,
  columns for each recorded span tag (host phase seconds), the step's
  tokens / swap stall, and the boundary loss readbacks;
- per-request serving timelines: admit -> prefill (TTFT) -> ticks ->
  finish, with waits and reasons;
- a checkpoint / restore / preempt timeline (ISSUE 7): snapshot
  begin/commit pairs with the commit-fence wait, corruption fallbacks,
  the preemption signal + final snapshot, elastic resumes — the
  elastic-serving lifecycle (ISSUE 11): drain -> snapshot -> restore
  -> requeue, aborts, replica kills and pool scale events — and the
  fault-tolerant training lifecycle (ISSUE 15): give the supervisor's
  dump and the workers' dumps together and the same table stitches
  die -> detect (rank_exit/rank_hang) -> teardown -> shrunk restart ->
  resume, stamped with each epoch's restart_epoch;
- a swap-tier I/O summary per step (bytes in/out, drain waits);
- request-scoped distributed traces (ISSUE 12): given N dump files
  TOGETHER (``view.py dumpA.jsonl dumpB.jsonl``), events are merged,
  deduplicated and stitched by ``trace_id`` into one cross-replica
  timeline per request — "born on replica 0, killed mid-verify,
  restored on replica 2, finished";
- cluster fences (ISSUE 12): the per-rank step-time skew table the
  cross-rank aggregation recorded at each fence;
- the trailing raw events with ``--events N``.

Pure stdlib + host-side JSON — the viewer never imports jax, so it runs
anywhere the dump landed (a dev laptop, a CI artifact store);
tests/test_metric_names.py pins the import chain jax-free.
"""

import argparse
import json
import os
import sys
from collections import OrderedDict, defaultdict


def load_dump(path):
    """Returns (header_or_None, events). Unparseable lines are skipped
    with a count so a truncated dump still renders."""
    header = None
    events = []
    skipped = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(obj, dict):
                skipped += 1
                continue
            if obj.get("kind") == "dump_header" and header is None:
                header = obj
            else:
                events.append(obj)
    return header, events, skipped


def load_dumps(paths):
    """Merge N dumps into one event stream: events deduplicate on
    ``(seq, ts, kind)`` (two dumps of the SAME recorder ring overlap —
    e.g. a mid-run anomaly dump plus an end-of-run one) and sort by
    wall clock then sequence, which also interleaves dumps from
    DIFFERENT processes/replicas onto one timeline. Returns
    ``(headers, events, skipped)`` with one (path, header) per file
    that had one."""
    headers, events, skipped = [], [], 0
    seen = set()
    for path in paths:
        header, evs, sk = load_dump(path)
        skipped += sk
        if header is not None:
            headers.append((path, header))
        for ev in evs:
            key = (ev.get("seq"), ev.get("ts"), ev.get("kind"))
            if ev.get("seq") is not None:
                if key in seen:
                    continue
                seen.add(key)
            events.append(ev)
    events.sort(key=lambda e: (e.get("ts") or 0.0, e.get("seq") or 0))
    return headers, events, skipped


def _fmt(v, width):
    if v is None or v == "":
        s = "-"
    elif isinstance(v, float):
        s = f"{v:.4g}"
    else:
        s = str(v)
    if len(s) > width:
        s = s[:width - 1] + "…"
    return s.rjust(width)


def _table(headers, rows, out):
    widths = [max(len(str(h)), 10) for h in headers]
    out.append("  " + " ".join(_fmt(h, w) for h, w in
                               zip(headers, widths)))
    for row in rows:
        out.append("  " + " ".join(_fmt(v, w) for v, w in
                                   zip(row, widths)))


def render_header(header, out):
    if header is None:
        out.append("no dump header (raw event stream)")
        return
    det = header.get("detail") or {}
    out.append(f"flight dump #{header.get('dump_id')} — rule "
               f"{header.get('rule')!r} (source "
               f"{header.get('source')}, {header.get('n_events')} "
               f"events)")
    if det:
        out.append("  trigger: " + ", ".join(
            f"{k}={det[k]!r}" if isinstance(det[k], str)
            else f"{k}={_fmt(det[k], 12).strip()}" for k in det))


def render_steps(events, out):
    """Per-step phase attribution: span tags as columns (seconds summed
    per step), plus tokens, swap stall and the boundary loss."""
    steps = OrderedDict()          # step -> {col: value}
    tags = []
    for ev in events:
        step = ev.get("step")
        if step is None:
            continue
        row = steps.setdefault(step, defaultdict(float))
        kind = ev.get("kind")
        if kind == "span":
            tag = ev.get("tag", "?")
            if tag not in tags:
                tags.append(tag)
            row[("span", tag)] += ev.get("dur_s") or 0.0
        elif kind == "step":
            row["tokens"] = ev.get("tokens")
            if ev.get("swap_stall_s") is not None:
                row["swap_stall_s"] = ev["swap_stall_s"]
            if ev.get("comm_intra_bytes") is not None \
                    or ev.get("comm_inter_bytes") is not None:
                # hierarchical comm cost model (ISSUE 10): bytes this
                # step put on the wire, fast + slow links
                row["comm_mb"] = ((ev.get("comm_intra_bytes") or 0)
                                  + (ev.get("comm_inter_bytes") or 0)) \
                    / 2**20
        elif kind == "onebit_freeze":
            row["comm_phase"] = "freeze"
        elif kind == "loss":
            row["loss"] = ev.get("loss")
        elif kind == "window":
            row["window_step_s"] = ev.get("step_s")
        elif kind == "anomaly":
            row["anomaly"] = ev.get("rule")
    if not steps:
        return
    out.append("")
    out.append("per-step phase attribution (host seconds per span tag):")
    extra = [c for c in ("comm_mb", "comm_phase")
             if any(c in row for row in steps.values())]
    headers = (["step"] + [t.replace("train/", "") for t in tags]
               + ["window_step_s", "tokens", "swap_stall_s"] + extra
               + ["loss", "anomaly"])
    rows = []
    for step, row in steps.items():
        rows.append([step] + [row.get(("span", t), "") for t in tags]
                    + [row.get("window_step_s", ""),
                       row.get("tokens", ""),
                       row.get("swap_stall_s", "")]
                    + [row.get(c, "") for c in extra]
                    + [row.get("loss", ""),
                       row.get("anomaly", "")])
    _table(headers, rows, out)


def render_requests(events, out):
    """Per-request serving timelines from admit/prefill/finish events,
    with the global tick stream summarized."""
    reqs = OrderedDict()           # rid -> fields
    ticks = 0
    tick_steps = 0
    spec_rounds = 0
    exhausted = 0
    t0 = None
    for ev in events:
        kind = ev.get("kind")
        if kind in ("admit", "prefill", "finish", "tick", "spec_round",
                    "pool_exhausted") and t0 is None:
            t0 = ev.get("ts")
        if kind == "admit":
            r = reqs.setdefault(ev.get("rid"), {})
            r["t_admit"] = ev.get("ts")
            r["slot"] = ev.get("slot")
            r["pages"] = ev.get("pages")
            r["wait_s"] = ev.get("wait_s")
        elif kind == "prefill":
            r = reqs.setdefault(ev.get("rid"), {})
            r["prompt_tokens"] = ev.get("prompt_tokens")
            r["ttft_s"] = ev.get("ttft_s")
        elif kind == "finish":
            r = reqs.setdefault(ev.get("rid"), {})
            r["t_finish"] = ev.get("ts")
            r["reason"] = ev.get("reason")
            r["generated"] = ev.get("generated")
        elif kind == "tick":
            ticks += 1
            tick_steps += ev.get("steps") or 0
        elif kind == "spec_round":
            # one speculative verify dispatch = one decode step that
            # commits up to rows tokens
            ticks += 1
            tick_steps += 1
            spec_rounds += 1
        elif kind == "pool_exhausted":
            exhausted += 1
    if not reqs and not ticks:
        return
    out.append("")
    out.append(f"serving: {len(reqs)} requests in window, {ticks} ticks"
               f" ({tick_steps} decode steps)"
               + (f", {spec_rounds} speculative verify rounds"
                  if spec_rounds else "")
               + (f", {exhausted} pool-exhausted admissions"
                  if exhausted else ""))
    if not reqs:
        return
    out.append("per-request timelines (t relative to first serving "
               "event):")
    headers = ["rid", "t_admit", "slot", "pages", "wait_s",
               "prompt_toks", "ttft_s", "t_finish", "reason", "toks"]
    rows = []
    for rid, r in reqs.items():
        rel = (lambda t: (t - t0) if (t is not None and t0 is not None)
               else None)
        rows.append([rid, rel(r.get("t_admit")), r.get("slot"),
                     r.get("pages"), r.get("wait_s"),
                     r.get("prompt_tokens"), r.get("ttft_s"),
                     rel(r.get("t_finish")), r.get("reason"),
                     r.get("generated")])
    _table(headers, rows, out)


def render_ckpt(events, out):
    """Checkpoint / restore / preemption timeline (ISSUE 7): one row
    per elastic lifecycle event — async snapshot begins and commits
    (with the commit-fence wait), aborts, resume-time validation
    failures, the preemption signal and its final snapshot, and the
    resume itself."""
    kinds = ("ckpt_begin", "ckpt_commit", "ckpt_abort", "ckpt_corrupt",
             "preempt_signal", "preempt", "resume",
             # elastic serving lifecycle (ISSUE 11): the
             # drain -> snapshot -> restore -> requeue chain plus the
             # replica-pool scale/kill incidents ride the same timeline
             "serving_drain", "serving_snapshot", "serving_restore",
             "serving_requeue", "serving_abort", "replica_scale",
             "replica_kill",
             # fault-tolerant training lifecycle (ISSUE 15): the
             # die -> detect -> shrink -> resume chain — supervisor
             # events (spawn/rank_exit/world_down/restart/crash_loop)
             # merged with the workers' own rank_hang/restart_epoch
             # breadcrumbs onto one timeline
             "supervisor_spawn", "rank_exit", "rank_hang", "world_down",
             "restart", "crash_loop", "restart_epoch")
    rows = []
    t0 = None
    for ev in events:
        kind = ev.get("kind")
        if kind not in kinds:
            continue
        if t0 is None:
            t0 = ev.get("ts")
        detail = ""
        if kind == "serving_drain":
            detail = (f"{ev.get('drained', 0)} drained, "
                      f"{ev.get('left', 0)} left"
                      + (", snapshotted" if ev.get("snapshotted")
                         else ", NO snapshot"))
        elif kind == "serving_snapshot":
            detail = (f"{ev.get('requests', '?')} req "
                      f"({ev.get('slots', '?')} slots + "
                      f"{ev.get('queued', '?')} queued), "
                      f"{ev.get('pages', '?')} pages")
        elif kind == "serving_restore":
            detail = (f"{ev.get('restored', 0)} direct + "
                      f"{ev.get('requeued', 0)} requeued, "
                      f"{ev.get('pages', 0)} pages, "
                      f"{ev.get('restore_s', 0):.4g}s")
            if ev.get("dropped_prefix_pages"):
                detail += (f", {ev['dropped_prefix_pages']} prefix "
                           f"pages dropped")
        elif kind == "serving_requeue":
            detail = f"rid {ev.get('rid')!r}"
            if ev.get("outcome"):
                detail += (f" {ev['outcome']} "
                           f"(attempt {ev.get('attempts', '?')})")
            if ev.get("committed") is not None:
                detail += f", {ev['committed']} committed tokens kept"
        elif kind == "serving_abort":
            detail = (f"rid {ev.get('rid')!r} from "
                      f"{ev.get('where', '?')}, "
                      f"{ev.get('generated', 0)} tokens generated")
        elif kind == "replica_scale":
            detail = (f"{ev.get('direction')} -> "
                      f"{ev.get('replicas', '?')} replicas "
                      f"(replica {ev.get('replica')}, "
                      f"{ev.get('reason', '')})")
        elif kind == "replica_kill":
            detail = (f"replica {ev.get('replica')}: "
                      f"{str(ev.get('reason', ''))[:40]}")
        elif kind == "ckpt_begin":
            detail = f"{ev.get('files', '?')} files, " \
                     f"{ev.get('from_swapfiles', 0)} from swap tier"
        elif kind == "ckpt_commit":
            detail = f"wait {ev.get('wait_s', 0):.4g}s" \
                     + (", fsync" if ev.get("fsync") else "")
        elif kind in ("ckpt_abort", "ckpt_corrupt"):
            detail = str(ev.get("reason", ""))[:40]
        elif kind == "preempt_signal":
            detail = f"sig {ev.get('signal')}, grace " \
                     f"{ev.get('grace_s', '?')}s"
        elif kind == "preempt":
            detail = "final snapshot committed" if ev.get("snapshotted") \
                else "NO final snapshot"
        elif kind == "resume":
            detail = f"dp {ev.get('from_dp')}→{ev.get('to_dp')}, " \
                     f"micro {ev.get('micro')} gas {ev.get('grad_accum')}"
            if ev.get("fell_back"):
                detail += f", {ev['fell_back']} corrupt skipped"
        elif kind == "supervisor_spawn":
            detail = (f"world {ev.get('world')}, epoch "
                      f"{ev.get('restart_epoch')}, coordinator "
                      f":{ev.get('port')}")
        elif kind == "rank_exit":
            detail = (f"rank {ev.get('rank')} down: "
                      f"{ev.get('reason', '?')} (epoch "
                      f"{ev.get('restart_epoch')})")
        elif kind == "rank_hang":
            detail = (f"rank {ev.get('rank')} blocked "
                      f"{ev.get('blocked_s', 0):.4g}s in "
                      f"{ev.get('region', '?')} (deadline "
                      f"{ev.get('deadline_s', '?')}s)")
        elif kind == "world_down":
            detail = (f"{ev.get('survivors_torn_down', 0)} survivors "
                      f"torn down, {ev.get('lost', '?')} rank(s) lost")
        elif kind == "restart":
            detail = (f"world {ev.get('world_from')}→"
                      f"{ev.get('world_to')}, epoch "
                      f"{ev.get('restart_epoch')}, backoff "
                      f"{ev.get('backoff_s', 0):.4g}s "
                      f"({ev.get('reason', '')})")
        elif kind == "crash_loop":
            detail = (f"{ev.get('restarts')} restart(s) spent (max "
                      f"{ev.get('max_restarts')}), last "
                      f"{ev.get('last_reason', '?')}")
        elif kind == "restart_epoch":
            detail = (f"worker up in epoch {ev.get('epoch')}, world "
                      f"{ev.get('world')}")
        rows.append([
            None if t0 is None or ev.get("ts") is None
            else ev["ts"] - t0,
            kind, ev.get("step"), ev.get("tag", ev.get("dir", "")),
            (ev.get("bytes") or 0) / 2**20 if "bytes" in ev else "",
            detail])
    if not rows:
        return
    out.append("")
    out.append("checkpoint / restore / preempt timeline (t relative to "
               "first ckpt event):")
    # the serving-elastic kinds (and their details) outgrow the
    # default 10-char column — size both to their longest row (detail
    # capped so one verbose reason can't blow up the table)
    ev_w = max(len("event"), *(len(str(r[1])) for r in rows))
    det_w = min(max(10, *(len(str(r[5])) for r in rows)), 60)
    _table(["t", "event".ljust(ev_w), "step", "tag", "mb",
            "detail".ljust(det_w)], rows, out)


def render_swap(events, out):
    """Swap-tier I/O per step: bytes written/read, cache hits, drains."""
    per_step = OrderedDict()
    seen = False
    for ev in events:
        kind = ev.get("kind")
        if kind not in ("swap_out", "swap_in", "swap_drain"):
            continue
        seen = True
        row = per_step.setdefault(ev.get("step"), defaultdict(float))
        if kind == "swap_out":
            row["write_mb"] += (ev.get("bytes") or 0) / 2**20
            row["out_leaves"] += ev.get("leaves") or 0
        elif kind == "swap_in":
            row["read_mb"] += (ev.get("bytes_read") or 0) / 2**20
            row["cache_mb"] += (ev.get("cache_hit_bytes") or 0) / 2**20
            row["in_leaves"] += ev.get("leaves") or 0
        elif kind == "swap_drain":
            row["drain_s"] += ev.get("wait_s") or 0.0
    if not seen:
        return
    out.append("")
    out.append("swap-tier I/O per step:")
    headers = ["step", "write_mb", "read_mb", "cache_mb", "out_leaves",
               "in_leaves", "drain_s"]
    rows = [[step] + [row.get(h, "") for h in headers[1:]]
            for step, row in per_step.items()]
    _table(headers, rows, out)


# lifecycle kinds that carry a single ``trace`` field, and the batch
# kinds whose ``traces`` list names every request they touched.
# ISSUE 14: the disaggregated lifecycle rides the same stitching —
# router_route (admission decision) -> admit/prefill on the
# prefill-role replica -> handoff_out -> handoff_in on the decode-role
# replica -> ticks -> finish; router_block marks admissions deferred
# on decode-pool pressure.
TRACE_POINT_KINDS = ("admit", "prefill", "finish", "serving_abort",
                     "serving_requeue", "pool_exhausted",
                     "router_route", "router_block", "handoff_out",
                     "handoff_in")
TRACE_SET_KINDS = ("serving_snapshot", "serving_restore")


def trace_timelines(events):
    """trace_id -> ordered event list (the stitching primitive the
    tests drive directly): lifecycle events attach by their ``trace``
    field, snapshot/restore events by membership in their ``traces``
    list. Events without a trace are ignored — a request admitted
    before tracing existed simply has no timeline."""
    traces = OrderedDict()
    for ev in events:
        kind = ev.get("kind")
        if kind in TRACE_POINT_KINDS and ev.get("trace") is not None:
            traces.setdefault(ev["trace"], []).append(ev)
        elif kind in TRACE_SET_KINDS:
            for tid in ev.get("traces") or ():
                if tid is not None:
                    traces.setdefault(tid, []).append(ev)
    return traces


def _trace_outcome(evs):
    for ev in reversed(evs):
        if ev.get("kind") == "finish":
            return f"finished ({ev.get('reason')})"
        if ev.get("kind") == "serving_abort":
            return "aborted"
        if ev.get("kind") == "serving_requeue" \
                and ev.get("outcome") == "dropped":
            # the pool's retry budget ran out — a TERMINAL loss, the
            # trace an operator is most likely hunting for
            return f"lost (dropped after {ev.get('attempts', '?')} " \
                   f"attempts)"
    return "open"


def render_traces(events, out):
    """Request-scoped distributed traces (ISSUE 12): a summary row per
    trace_id, then a stitched per-event timeline for every trace that
    crossed a replica boundary or was requeued — the "born on replica
    0, restored on replica 2, finished" story."""
    traces = trace_timelines(events)
    if not traces:
        return
    ts_all = [ev["ts"] for evs in traces.values() for ev in evs
              if ev.get("ts") is not None]
    t0 = min(ts_all) if ts_all else None
    rel = (lambda t: (t - t0) if (t is not None and t0 is not None)
           else None)
    out.append("")
    out.append(f"request traces ({len(traces)} trace_id(s) stitched "
               f"across the given dumps):")
    headers = ["trace", "rid", "replicas", "events", "requeues",
               "outcome", "t_first", "t_last"]
    rows = []
    for tid, evs in traces.items():
        rid = next((ev.get("rid") for ev in evs
                    if ev.get("rid") is not None), None)
        reps = sorted({ev["replica"] for ev in evs
                       if ev.get("replica") is not None})
        rows.append([
            tid, rid, ",".join(str(r) for r in reps) or "-", len(evs),
            sum(ev.get("kind") == "serving_requeue" for ev in evs),
            _trace_outcome(evs),
            rel(evs[0].get("ts")), rel(evs[-1].get("ts"))])
    _table(headers, rows, out)
    for tid, evs in traces.items():
        reps = {ev["replica"] for ev in evs
                if ev.get("replica") is not None}
        crossed = len(reps) > 1 or any(
            ev.get("kind") == "serving_requeue" for ev in evs)
        if not crossed:
            continue
        out.append(f"  trace {tid} (rid "
                   f"{next((ev.get('rid') for ev in evs if ev.get('rid') is not None), '?')!r}):")
        for ev in evs:
            kind = ev.get("kind")
            rep = ev.get("replica")
            where = f"replica {rep}" if rep is not None else "-"
            bits = []
            for k in ("slot", "prompt_tokens", "ttft_s", "reason",
                      "generated", "outcome", "attempts", "committed",
                      "remaining", "restored", "requeued", "tag",
                      "engine", "pos"):
                if ev.get(k) is not None:
                    v = ev[k]
                    bits.append(f"{k}={v:.4g}" if isinstance(v, float)
                                else f"{k}={v}")
            t = rel(ev.get("ts"))
            out.append(f"    +{t:9.3f}s  {kind:<17} [{where}] "
                       + ", ".join(bits)
                       if t is not None else
                       f"    {'':>10}   {kind:<17} [{where}] "
                       + ", ".join(bits))


def render_disagg(events, out):
    """Disaggregated-serving summary (ISSUE 14): routing decisions by
    reason, handoff volume, transport requeues, and admissions the
    router deferred on decode-pool pressure — per-trace detail rides
    the stitched timelines above (prefill→handoff→decode crosses a
    replica boundary, so every handed-off trace prints there)."""
    routed = defaultdict(int)
    handoffs = requeues = blocked = 0
    for ev in events:
        kind = ev.get("kind")
        if kind == "router_route":
            routed[ev.get("reason") or "?"] += 1
        elif kind == "handoff_in":
            handoffs += 1
        elif kind == "router_block":
            blocked += 1
        elif kind == "serving_requeue" \
                and ev.get("outcome") == "scheduled":
            requeues += 1
    if not routed and not handoffs:
        return
    out.append("")
    by_reason = ", ".join(f"{n} by {r}" for r, n in sorted(routed.items()))
    out.append(f"disaggregated serving: {sum(routed.values())} prompts "
               f"routed ({by_reason}), {handoffs} prefill→decode "
               f"handoffs, {requeues} requeues, {blocked} admissions "
               f"deferred on decode-pool pressure")


def render_cluster(events, out):
    """Cluster fences (ISSUE 12): the per-rank step-time skew table
    the cross-rank aggregation recorded on rank 0 at each fence."""
    fences = [ev for ev in events if ev.get("kind") == "cluster_fence"]
    if not fences:
        return
    world = max(len(ev.get("step_time_per_rank") or ()) for ev in fences)
    out.append("")
    out.append(f"cluster fences (world {world}; per-rank step time, s):")
    headers = ["step", "world"] + [f"rank{r}_step_s" for r in range(world)]         + ["loss_rank0"]
    rows = []
    for ev in fences:
        st = list(ev.get("step_time_per_rank") or ())
        st += [None] * (world - len(st))
        losses = ev.get("loss_per_rank") or [None]
        rows.append([ev.get("step"), ev.get("world")] + st + [losses[0]])
    _table(headers, rows, out)


def render(paths, tail_events=0):
    """The full report as a list of lines (the CLI joins and prints).
    ``paths`` may be one dump path (str or PathLike, the pre-ISSUE-12
    signature) or a list of them — multiple dumps merge onto one
    timeline (cross-replica trace stitching)."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    headers, events, skipped = load_dumps(paths)
    out = []
    if not headers:
        out.append("no dump header (raw event stream)")
    for path, header in headers:
        if len(headers) > 1:
            out.append(f"[{path}]")
        render_header(header, out)
    if skipped:
        out.append(f"({skipped} unparseable line(s) skipped)")
    if not events:
        out.append("no events")
        return out
    render_steps(events, out)
    render_requests(events, out)
    render_disagg(events, out)
    render_traces(events, out)
    render_cluster(events, out)
    render_ckpt(events, out)
    render_swap(events, out)
    plans = [ev for ev in events
             if ev.get("kind") in ("overlap_bucket_plan",
                                   "prefetch_layer_plan",
                                   "comm_hierarchy_plan",
                                   "comm_hierarchy_fallback")]
    if plans:
        out.append("")
        out.append("comm bucket plans (trace-time):")
        for ev in plans:
            out.append("  " + json.dumps(
                {k: v for k, v in ev.items() if k not in ("ts", "seq")}))
    if tail_events:
        out.append("")
        out.append(f"last {min(tail_events, len(events))} raw events:")
        for ev in events[-tail_events:]:
            out.append("  " + json.dumps(ev))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.telemetry.view",
        description=__doc__.splitlines()[0])
    ap.add_argument("dump", nargs="+",
                    help="flight-recorder dump(s) (JSONL) — give several "
                         "to merge them onto one timeline (cross-replica "
                         "trace stitching)")
    ap.add_argument("--events", type=int, default=0, metavar="N",
                    help="also print the last N raw events")
    ap.add_argument("--format", choices=("text", "perfetto"),
                    default="text", dest="fmt",
                    help="text report (default) or a Chrome "
                         "trace-event JSON for ui.perfetto.dev / "
                         "chrome://tracing (telemetry/perfetto.py)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)
    try:
        if args.fmt == "perfetto":
            # lazy: perfetto imports load_dump from THIS module
            from deepspeed_tpu.telemetry import perfetto
            text = perfetto.dumps(perfetto.export(args.dump))
        else:
            text = "\n".join(render(args.dump,
                                    tail_events=args.events))
    except OSError as e:
        print(f"cannot read {' '.join(args.dump)}: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
