"""Process-wide metrics registry: counters, gauges, histograms.

Design rules (the sync-discipline contract, docs/observability.md):

- recording is host-only and cheap — a lock acquire plus a float store;
  callers in hot loops (the engine's per-step path, the serving
  scheduler tick) never pay a device sync to record;
- histograms keep a bounded reservoir (most-recent ``maxlen``
  observations) plus exact running count/sum/min/max, so percentiles
  are over recent behaviour while totals stay exact;
- everything is thread-safe: the serving scheduler and a training loop
  may record into the same registry concurrently.

Exporters are pull-based: they serialize a ``snapshot()`` — they never
hold the registry lock across I/O.
"""

import json
import math
import os
import threading
import time
from collections import deque


def _process_rank():
    """This process's rank for event tagging: the launcher's env, else
    the jax process index — via utils.logging._process_index, which
    asks WITHOUT initializing a backend (a bare jax.process_index()
    before jax.distributed.initialize would pin every host to rank 0
    and break the multi-host rendezvous)."""
    for var in ("RANK", "PMI_RANK", "SLURM_PROCID"):
        if os.environ.get(var):
            try:
                return int(os.environ[var])
            except ValueError:
                pass
    from deepspeed_tpu.utils.logging import _process_index
    return int(_process_index())


class Counter:
    """Monotonic float counter."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock

    def inc(self, n=1.0):
        with self._lock:
            self.value += n


class Gauge:
    """Last-value-wins scalar; ``set_max`` keeps a high-water mark."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock):
        self.value = 0.0
        self._lock = lock

    def set(self, v):
        with self._lock:
            self.value = float(v)   # sync-ok: contract — host scalars only

    def set_max(self, v):
        with self._lock:
            self.value = max(self.value, float(v))  # sync-ok: host scalars


class Histogram:
    """Bounded-reservoir histogram with exact count/sum/min/max."""

    __slots__ = ("count", "sum", "min", "max", "_values", "_lock")

    def __init__(self, lock, maxlen=1024):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._values = deque(maxlen=maxlen)
        self._lock = lock

    def observe(self, v):
        v = float(v)                # sync-ok: contract — host scalars only
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            self._values.append(v)

    def values(self):
        """Copy of the bounded reservoir (most-recent observations) —
        cross-replica aggregation (ISSUE 12: ReplicaPool pool-level
        TTFT percentiles) merges raw reservoirs instead of averaging
        already-summarized percentiles."""
        with self._lock:
            return list(self._values)

    def summary(self):
        with self._lock:
            vals = sorted(self._values)
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
            # inside the lock: a concurrent observe() between the copy
            # and this read would make 'last' inconsistent with the
            # rest of the snapshot (last > max)
            last = self._values[-1] if self._values else None
        if not vals:
            return {"count": 0, "sum": 0.0}

        def pct(q):
            return vals[min(len(vals) - 1,
                            max(0, int(round(q / 100.0 * (len(vals) - 1)))))]
        return {
            "count": count,
            "sum": total,
            "mean": total / max(count, 1),
            "min": lo,
            "max": hi,
            "p50": pct(50),
            "p90": pct(90),
            "p99": pct(99),
            "last": last,
        }


class MetricsRegistry:
    """Named metric store. Metric names are ``/``-separated paths
    (``train/step_time_s``, ``serving/ttft_s``); the first segment is
    the subsystem, which exporters may filter on."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}

    def counter(self, name) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self._lock)
            return c

    def gauge(self, name) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(self._lock)
            return g

    def histogram(self, name, maxlen=1024) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(self._lock, maxlen)
            return h

    def peek_gauge(self, name):
        """Current gauge value WITHOUT creating the gauge (None when it
        was never set) — per-fence readers (telemetry/cluster.py) must
        neither pollute the registry with empty metrics nor pay a full
        snapshot() to read three values."""
        with self._lock:
            g = self._gauges.get(name)
            return None if g is None else g.value

    def peek_histogram_last(self, name):
        """Most recent observation of a histogram, or None when absent
        or empty — same per-fence-reader rationale as peek_gauge."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None or not h._values:
                return None
            return h._values[-1]

    def peek_histogram_count(self, name):
        """Lifetime observation count of a histogram WITHOUT creating
        it (0 when absent) — the SLO plane's new-tail cursor
        (telemetry/slo.py feed_counted) polls this at tick cadence."""
        with self._lock:
            h = self._histograms.get(name)
            return 0 if h is None else h.count

    def peek_histogram_values(self, name):
        """Reservoir copy WITHOUT creating the histogram ([] when
        absent) — cross-replica mergers (ReplicaPool.metrics_snapshot)
        must not seed idle replicas' registries with phantom
        zero-count metrics."""
        with self._lock:
            h = self._histograms.get(name)
            return [] if h is None else list(h._values)

    def snapshot(self, prefix=None):
        """One JSON-able dict of everything (optionally filtered to
        names starting with ``prefix``)."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()}
            hists = dict(self._histograms)
        if prefix:
            counters = {k: v for k, v in counters.items()
                        if k.startswith(prefix)}
            gauges = {k: v for k, v in gauges.items()
                      if k.startswith(prefix)}
            hists = {k: v for k, v in hists.items()
                     if k.startswith(prefix)}
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.summary() for k, h in hists.items()},
        }

    def reset(self):
        """Drop every metric (snapshot-and-reset windows)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry — the engine, spans, and serving
    default here so one JSONL stream carries every subsystem."""
    return _default


def record_comm_exposure(site, exposed_s, hidden_s, registry=None):
    """Per-site communication-exposure counters (ISSUE 8):
    ``comm/<site>/exposed_s`` is wall time a step spent WAITING on
    collectives (comm the schedule failed to hide), ``hidden_s`` is
    collective stream time that overlapped compute. Fed by measurement
    harnesses (tests/perf/prefetch_bench.py's gather-wait vs compute
    decomposition) — host floats only, never a device sync."""
    r = registry or default_registry()
    r.counter(f"comm/{site}/exposed_s").inc(max(0.0, exposed_s))
    r.counter(f"comm/{site}/hidden_s").inc(max(0.0, hidden_s))


# ---------------------------------------------------------------- export

class JsonlExporter:
    """Appends one JSON line per export: wall-clock timestamp, rank,
    step, and the full snapshot — the multi-process-mergeable stream
    (each rank writes its own file; events self-identify).

    Size-bounded rotation (ISSUE 6 satellite): when ``max_bytes`` > 0
    and the file crosses it after an export, the stream rotates
    logrotate-style — ``path`` → ``path.1`` → … → ``path.{max_files-1}``
    and the oldest drops — so a multi-hour run holds at most
    ``max_files × max_bytes`` of scalar history on disk."""

    def __init__(self, path, registry=None, max_bytes=0, max_files=4):
        self.path = path
        self.registry = registry or default_registry()
        self.rank = _process_rank()
        self.max_bytes = int(max_bytes or 0)
        self.max_files = max(int(max_files), 1)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a")

    def _rotate(self):
        self._fh.close()
        # shift path.{k} -> path.{k+1}, oldest falls off the end
        for k in range(self.max_files - 1, 0, -1):
            src = self.path if k == 1 else f"{self.path}.{k - 1}"
            dst = f"{self.path}.{k}"
            if os.path.exists(src):
                os.replace(src, dst)
        if self.max_files == 1:          # bounded to ONE file: truncate
            open(self.path, "w").close()
        self._fh = open(self.path, "a")

    def export(self, step=None, snapshot=None):
        snap = snapshot if snapshot is not None else self.registry.snapshot()
        self._fh.write(json.dumps({
            "ts": time.time(),
            "rank": self.rank,
            "step": step,
            "metrics": snap,
        }) + "\n")
        self._fh.flush()
        if self.max_bytes and self._fh.tell() >= self.max_bytes:
            self._rotate()

    def close(self):
        self._fh.close()


class SummaryBridge:
    """Bridges a snapshot into the existing ``SummaryEventWriter``
    (TensorBoard when available, JSONL events otherwise): counters and
    gauges as plain scalars, histograms as p50/p90/p99/mean scalars."""

    def __init__(self, writer, registry=None):
        self.writer = writer
        self.registry = registry or default_registry()

    def export(self, step, snapshot=None):
        snap = snapshot if snapshot is not None else self.registry.snapshot()
        w = self.writer
        for k, v in snap["counters"].items():
            w.add_scalar(k, v, step)
        for k, v in snap["gauges"].items():
            w.add_scalar(k, v, step)
        for k, s in snap["histograms"].items():
            if not s.get("count"):
                continue
            for stat in ("mean", "p50", "p90", "p99"):
                w.add_scalar(f"{k}/{stat}", s[stat], step)
        w.flush()


def _prom_name(name):
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    n = "".join(out)
    return ("_" + n) if n[:1].isdigit() else n


def _prom_escape_label(value):
    """Escape a label VALUE per the exposition format (backslash,
    double-quote and newline must be escaped inside the quotes) — real
    scrapers reject unescaped ones."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_escape_help(text):
    """HELP text escaping: backslash and newline only (HELP lines are
    unquoted)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_header(lines, prom_name, metric_name, kind):
    """``# HELP`` then ``# TYPE`` (the order scrapers expect) for one
    metric family. The help text carries the original ``/``-separated
    metric path — the name mangling is lossy, the HELP line is not."""
    lines.append(f"# HELP {prom_name} deepspeed_tpu metric "
                 f"{_prom_escape_help(metric_name)}")
    lines.append(f"# TYPE {prom_name} {kind}")


def prometheus_text(registry=None, snapshot=None):
    """Prometheus exposition-format text dump of a snapshot: counters
    as ``counter``, gauges as ``gauge``, histograms as ``summary``
    (quantiles + _sum/_count). Every family carries ``# HELP`` and
    ``# TYPE`` lines and label values are escaped, so real scrapers
    (prometheus, vmagent) parse the page cleanly (ISSUE 6 satellite)."""
    snap = snapshot if snapshot is not None else \
        (registry or default_registry()).snapshot()
    lines = []
    for k, v in sorted(snap["counters"].items()):
        n = _prom_name(k)
        _prom_header(lines, n, k, "counter")
        lines.append(f"{n} {v}")
    for k, v in sorted(snap["gauges"].items()):
        n = _prom_name(k)
        _prom_header(lines, n, k, "gauge")
        lines.append(f"{n} {v}")
    for k, s in sorted(snap["histograms"].items()):
        n = _prom_name(k)
        _prom_header(lines, n, k, "summary")
        for q, stat in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            if stat in s:
                lines.append(
                    f'{n}{{quantile="{_prom_escape_label(q)}"}} {s[stat]}')
        lines.append(f"{n}_sum {s.get('sum', 0.0)}")
        lines.append(f"{n}_count {s.get('count', 0)}")
    return "\n".join(lines) + "\n"
