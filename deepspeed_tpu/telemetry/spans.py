"""Async-safe phase spans + the programmatic XLA trace window.

The retired anti-pattern: ``SynchronizedWallClockTimer`` syncs the
device on every ``start``/``stop`` read, which serializes dispatch
against execution when wrapped around hot-loop phases (the reference's
``cuda.synchronize`` habit, utils/timer.py). Spans here never sync:

- ``span("tag")`` (host side) records the host wall time of the block
  into ``span/{tag}`` and emits a ``jax.profiler.TraceAnnotation`` so
  the block shows on the host timeline of an XLA trace. Around a jitted
  call this measures **dispatch** time (async under jit) — real device
  time for the block comes from the trace window or from a
  ``steps_per_print``-boundary fence the caller already pays.
- ``annotate("tag")`` (trace time) is ``jax.named_scope``: ops traced
  under it carry the tag in their HLO metadata, so device-side phase
  attribution (forward / backward / bucket-sync / prefetch-gather)
  lands in perfetto/xprof without any runtime cost.
- ``TraceWindow`` wraps ``jax.profiler.start_trace/stop_trace`` around
  a configured step range (``profiling.trace_dir`` +
  ``profiling.trace_steps``) — the one place a deliberate fence happens
  (at stop, so the captured steps' device work is in the trace).

ISSUE 19 adds the **causal span-id layer** under the distributed trace
plane: ``new_span_id()`` mints process-unique ids (pid-scoped, so ids
minted on different ranks never collide when their dump files merge)
and serving lifecycle events carry ``span_id``/``parent_span`` fields
that ``telemetry/perfetto.py`` stitches into one parent/child tree per
``trace_id`` — prefill on rank 0, transport encode/collective, adopt +
per-tick decode on rank N, finish — even though every leg landed in a
different per-role dump file. Minting is stdlib + a lock; nothing here
touches jax (the jax-free viewer contract covers the exporter that
consumes these ids).
"""

import contextlib
import itertools
import os
import threading
import time

from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.registry import default_registry
from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------- span ids
#
# A span id must be unique across EVERY process whose dump files end up
# merged in one Perfetto export (N ranks × supervisor restart epochs).
# uuid-per-span would work but costs an entropy read per serving event;
# a pid-prefixed counter is two orders cheaper and collision-free by
# construction: the pid names the process, the counter names the span.
# (Pid recycling across supervisor epochs is disambiguated by the
# startup-time nonce baked into the prefix.)

_span_counter = itertools.count(1)
_span_prefix = None
_span_lock = threading.Lock()


def new_span_id():
    """Mint a process-unique span id (``"<pid-hex><nonce>-<n>"``).
    Host-only and cheap — safe on the serving scheduler's per-request
    path. Thread-safe; ids from concurrent threads never collide."""
    global _span_prefix
    if _span_prefix is None:
        with _span_lock:
            if _span_prefix is None:
                _span_prefix = f"{os.getpid():x}{os.urandom(2).hex()}"
    return f"{_span_prefix}-{next(_span_counter)}"


def span_fields(span_id, parent_span=None):
    """The event-field convention of the trace plane: a dict to splat
    into a recorder event. ``parent_span=None`` marks a ROOT span —
    the exporter renders it as the request's top-level slice."""
    out = {"span_id": span_id}
    if parent_span is not None:
        out["parent_span"] = parent_span
    return out


def annotate(tag):
    """Trace-time scope: ops traced inside carry ``tag`` in HLO
    metadata (shows up in xprof/perfetto op names). Zero runtime cost —
    usable unconditionally inside jitted train fns."""
    import jax
    return jax.named_scope(tag)


@contextlib.contextmanager
def span(tag, registry=None, annotation=True, recorder=None):
    """Host-side phase span: wall time into ``span/{tag}`` plus a
    profiler TraceAnnotation, plus one ``span`` event in the flight
    recorder (the per-STEP record the histogram's aggregate view
    cannot reconstruct — recorder.py). NEVER syncs the device — around
    a jitted call this measures dispatch, by design (sync discipline,
    docs/observability.md). Async-safe: state lives on the stack, the
    registry/recorder lock per record; concurrent spans from other
    threads (e.g. the serving scheduler) interleave correctly."""
    reg = registry or default_registry()
    rec = recorder if recorder is not None else default_recorder()
    ann = None
    if annotation:
        try:
            import jax
            ann = jax.profiler.TraceAnnotation(tag)
            ann.__enter__()
        except Exception:   # profiler backends are optional
            ann = None
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        reg.histogram(f"span/{tag}").observe(dt)
        rec.record("span", tag=tag, dur_s=dt)


class TraceWindow:
    """Config-gated programmatic profiler window: capture steps
    ``[start, stop)`` of the training loop into ``trace_dir`` (xprof
    format — open in perfetto / tensorboard-profile). Start/stop are
    engine ``global_steps`` values as seen BEFORE the step runs.

    The window stops with a caller-supplied fence so the traced steps'
    device work is actually inside the capture; that one sync is the
    point of the window and never happens unless tracing was on."""

    def __init__(self, trace_dir, start_step, stop_step, registry=None):
        assert stop_step > start_step >= 0, (start_step, stop_step)
        self.trace_dir = trace_dir
        self.start_step = int(start_step)
        self.stop_step = int(stop_step)
        self.active = False
        self.done = False
        self._registry = registry or default_registry()

    @classmethod
    def from_config(cls, profiling_cfg):
        """None when the gate is off (no trace_dir or no trace_steps)."""
        if not getattr(profiling_cfg, "trace_dir", None):
            return None
        steps = getattr(profiling_cfg, "trace_steps", None)
        if not steps:
            return None
        return cls(profiling_cfg.trace_dir, steps[0], steps[1])

    def on_step_begin(self, step):
        if self.done or self.active or step < self.start_step \
                or step >= self.stop_step:
            return
        import jax
        try:
            jax.profiler.start_trace(self.trace_dir)
        except Exception as e:   # a second live trace, unwritable dir …
            logger.warning(f"trace window failed to start: {e}")
            self.done = True
            return
        self.active = True
        # a run that ends before stop_step-1 (crash, short loop) must
        # still finalize the capture — a dangling live trace writes no
        # artifact and blocks every later start_trace in the process
        import atexit
        atexit.register(self.close)
        self._registry.counter("profiling/trace_windows").inc()
        logger.info(f"[telemetry] XLA trace started (steps "
                    f"[{self.start_step}, {self.stop_step}) -> "
                    f"{self.trace_dir})")

    def on_step_end(self, step, fence=None):
        """``step`` is the same pre-run index passed to on_step_begin;
        ``fence`` (e.g. a loss readback) runs before stop_trace so the
        final step's device work lands in the capture."""
        if not self.active or step < self.stop_step - 1:
            return
        if fence is not None:
            try:
                fence()   # sync-ok: trace-window close, config-gated
            except Exception:
                pass
        self.close()

    def close(self):
        """Finalize an active capture (idempotent; also the atexit
        safety net for runs shorter than the configured window)."""
        if not self.active:
            return
        import atexit
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            logger.warning(f"trace window failed to stop: {e}")
        self.active = False
        self.done = True
        atexit.unregister(self.close)
        logger.info(f"[telemetry] XLA trace written to {self.trace_dir}")
