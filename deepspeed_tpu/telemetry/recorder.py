"""Flight recorder: a process-wide, bounded ring buffer of structured
events — the "what happened on THAT step/request" layer the aggregate
registry (registry.py) cannot answer.

The registry answers "how fast on average"; the recorder keeps the last
``capacity`` discrete events (step lifecycle with per-phase host
timings, swap-tier I/O, prefetch/overlap bucket plans, serving request
lifecycle) so that when something goes wrong — a NaN loss, a step-time
spike, a TTFT blowup — the watchdog (anomaly.py) can dump the recent
history to JSONL and ``python -m deepspeed_tpu.telemetry.view`` can
reconstruct the offending step or request.

Design rules (same sync-discipline contract as the registry):

- recording is host-only and cheap: one enabled-flag read, a dict
  build, a lock acquire, a deque append. Nothing here ever touches a
  device value — callers pass host scalars they already have;
- the ring is bounded (``deque(maxlen=capacity)``): a multi-day run
  holds the last ~capacity events and nothing more;
- everything is thread-safe: the serving scheduler, aio completion
  paths and a training loop may record concurrently;
- when disabled, ``record()`` is a single attribute read and return —
  the recorder-off cost in a hot loop is one branch.

Events are plain dicts: ``{"ts": wall_clock, "seq": monotonic_int,
"kind": str, ...payload}`` plus a ``"step"`` field injected from the
recorder's current training-step context when one is set. Kinds in use
(docs/observability.md has the full schema):

- ``span`` (tag, dur_s) — host phase timings from spans.span();
- ``step`` (step, tokens, swap_stall_s) / ``loss`` (step, loss) /
  ``window`` (step_s, steps) — engine step lifecycle;
- ``swap_out`` / ``swap_in`` / ``swap_drain`` — swap-tier I/O
  (runtime/swap_tensor/swapper.py);
- ``overlap_bucket_plan`` / ``prefetch_layer_plan`` — trace-time bucket
  planning (parallel/overlap.py, parallel/prefetch.py);
- ``admit`` / ``prefill`` / ``tick`` / ``finish`` / ``pool_exhausted``
  — serving request lifecycle (serving/engine.py);
- ``ckpt_begin`` / ``ckpt_commit`` / ``ckpt_abort`` / ``ckpt_corrupt``
  / ``preempt_signal`` / ``preempt`` / ``resume`` — elastic snapshot +
  preemption lifecycle (runtime/elastic, ISSUE 7);
- ``anomaly`` — appended by the watchdog after it dumps.
"""

import threading
import time
from collections import deque


class FlightRecorder:
    """Bounded, thread-safe ring of structured events."""

    def __init__(self, capacity=4096, enabled=True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._ring = deque(maxlen=max(int(capacity), 32))
        self._seq = 0
        self._step = None

    @property
    def capacity(self):
        return self._ring.maxlen

    def configure(self, enabled=None, capacity=None):
        """Reconfigure in place (the engine applies the
        ``monitor.flight_recorder`` block here). Shrinking/growing the
        capacity keeps the most recent events."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if capacity is not None and int(capacity) != self._ring.maxlen:
                self._ring = deque(self._ring,
                                   maxlen=max(int(capacity), 32))
        return self

    def set_step(self, step):
        """Set the training-step context stamped onto subsequent events
        (a plain int store — benign under concurrent readers)."""
        self._step = int(step) if step is not None else None

    def record(self, kind, **fields):
        """Append one event. Host scalars only — never pass a device
        array (the sync-discipline contract; test_sync_guard pins the
        module). No-op when disabled."""
        if not self.enabled:
            return
        ev = {"ts": time.time(), "kind": kind}
        step = self._step
        if step is not None and "step" not in fields:
            ev["step"] = step
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    def events(self):
        """A consistent copy of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    """The process-wide recorder — the engine, spans, swap tier and
    serving scheduler all default here so one ring carries every
    subsystem's recent history (what a post-anomaly dump needs)."""
    return _default
