"""Anomaly watchdog: fence-point rule evaluation + one-shot ring dumps.

The watchdog turns the flight recorder (recorder.py) into an incident
reporter: when a rule trips, it writes ONE JSONL dump of the ring —
the last ~capacity events leading up to the anomaly — and latches so a
persistent condition (a NaN loss that stays NaN, a saturated page pool)
produces exactly one dump, not one per step.

The cardinal rule, inherited from the telemetry sync discipline
(docs/observability.md): **the watchdog never forces a device sync.**
Every hook takes host scalars the caller already paid for at an
existing fence point:

- ``check_loss(v)`` — the engine's ``steps_per_print`` boundary, where
  the loss readback already happened (NaN/inf detection);
- ``observe_step_time(s)`` — the boundary window fold (outlier vs a
  rolling baseline);
- ``observe_swap_stall(s)`` — the per-step host stall timer the swap
  tier already keeps (outlier vs baseline, with an absolute floor);
- ``observe_ttft(s)`` / ``note_pool_exhausted()`` — the serving
  scheduler's admission sweep, whose prefill-logits readback is the
  TTFT measurement itself;
- ``observe_ckpt_stall(s)`` / ``note_ckpt_corrupt()`` /
  ``note_preempt()`` — the elastic snapshot layer (ISSUE 7): the
  commit-fence stall timer the engine already keeps, resume-time
  validation failures, and the preemption incident itself;
- ``note_rank_dead()`` / ``note_crash_loop()`` — the fault-tolerance
  plane (ISSUE 15): a rank's hard death or hung collective as
  observed by the hang watchdog (runtime/elastic/hang.py) or the
  launcher-level supervisor (runtime/elastic/supervisor.py), and the
  terminal exhausted-restart-budget incident.

Outlier rules keep a rolling baseline of recent NORMAL observations
(anomalous values never pollute their own baseline) and trip when a
value exceeds ``max(factor * baseline_mean, min_value)``; they re-arm
once a normal value is seen again. Dumps are numbered by a monotonic
``dump_id`` surfaced in ``snapshot()`` (and, for serving, in
``ContinuousBatcher.metrics_snapshot()``).
"""

import json
import math
import os
import threading
import time
from collections import deque

from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.registry import default_registry
from deepspeed_tpu.utils.logging import logger

_PROVENANCE = None


def _provenance_doc():
    """Cached host/build stamp for dump headers (ISSUE 19 satellite):
    a dump read days later off a shared scratch dir must answer "which
    box, which sha, which restart epoch" without archaeology. Reuses
    ``bench.provenance()`` when the repo-root module is importable
    (the git subprocess runs ONCE per process, not per dump); degrades
    to the same shape inline when it is not (installed package, no
    repo checkout)."""
    global _PROVENANCE
    if _PROVENANCE is None:
        try:
            from bench import provenance
            _PROVENANCE = provenance()
        except Exception:
            import platform
            import socket
            _PROVENANCE = {"git_sha": "unknown",
                           "hostname": socket.gethostname(),
                           "cpu_count": os.cpu_count(),
                           "jax_version": "unknown",
                           "python_version": platform.python_version()}
    return _PROVENANCE


class RollingOutlierRule:
    """Trip when a value exceeds ``max(factor * rolling_median,
    min_value)``; latch until a normal value re-arms. Values observed
    while the baseline is still warming (< ``min_samples``) only feed
    the baseline — and the baseline statistic is the MEDIAN, so a
    single extreme warm-up observation (a compile-inflated first
    window, a cold-cache first read) cannot poison the threshold the
    way a mean would."""

    def __init__(self, name, factor=3.0, min_value=0.0, window=64,
                 min_samples=8):
        assert factor > 1.0, (name, factor)
        self.name = name
        self.factor = factor
        self.min_value = min_value
        self.min_samples = max(int(min_samples), 1)
        self._baseline = deque(maxlen=max(int(window), self.min_samples))
        self._tripped = False

    def _median(self):
        vals = sorted(self._baseline)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0

    def threshold(self):
        """Current trip threshold, or None while warming."""
        if len(self._baseline) < self.min_samples:
            return None
        return max(self.factor * self._median(), self.min_value)

    def observe(self, v):
        """Returns a detail dict when this observation TRIPS the rule
        (first anomalous value after normal ones), else None."""
        thr = self.threshold()
        if thr is not None and v > thr:
            if self._tripped:
                return None              # latched: one dump per episode
            self._tripped = True
            return {"value": v, "threshold": thr,
                    "baseline_median": self._median(),
                    "baseline_n": len(self._baseline)}
        self._tripped = False
        self._baseline.append(v)
        return None


class StragglerRule:
    """Cluster rank-straggler rule (ISSUE 12): at each cluster fence it
    sees the per-rank step-time vector (``None``/NaN = rank did not
    measure this fence) and trips when one rank exceeds ``max(factor *
    median_of_the_OTHER_ranks, min_value)`` for ``fences`` CONSECUTIVE
    fences. The leave-one-out median matters at small world sizes: with
    2 ranks a whole-cluster median includes the straggler itself, so a
    rank 10x slower only reaches ~1.8x the median and a 2x factor would
    never fire. Latched per rank per episode — a persistently slow rank
    dumps once; a fence where it looks normal re-arms it."""

    def __init__(self, factor=2.0, min_value=0.0, fences=3):
        assert factor > 1.0, factor
        self.factor = factor
        self.min_value = min_value
        self.fences = max(int(fences), 1)
        self._streak = {}            # rank -> consecutive slow fences
        self._tripped = set()        # latched ranks

    def observe(self, per_rank):
        """``per_rank``: sequence of step-time seconds (None/NaN for
        unmeasured ranks). Returns a detail dict when the WORST newly
        over-threshold-for-K-fences rank trips, else None (other
        simultaneous stragglers latch silently this fence)."""
        vals = {r: float(v) for r, v in enumerate(per_rank)
                if v is not None and math.isfinite(v)}  # sync-ok: host
        if len(vals) < 2:
            # no comparison possible: CONSECUTIVE is broken for every
            # rank — freezing the streaks here would let slow fences
            # separated by arbitrary unmeasured gaps count as adjacent
            self._streak.clear()
            return None
        for r, _v in enumerate(per_rank):
            if r not in vals:
                # a rank that skipped measurement this fence breaks its
                # own consecutiveness (the latch stays: unmeasured is
                # not evidence of normality, only a normal fence re-arms)
                self._streak[r] = 0
        trips = []
        for r, v in vals.items():
            others = sorted(x for q, x in vals.items() if q != r)
            n = len(others)
            med = others[n // 2] if n % 2 \
                else (others[n // 2 - 1] + others[n // 2]) / 2.0
            thr = max(self.factor * med, self.min_value)
            if v > thr:
                self._streak[r] = self._streak.get(r, 0) + 1
                if self._streak[r] >= self.fences \
                        and r not in self._tripped:
                    trips.append({"rank": r, "value": v,
                                  "threshold": thr,
                                  "peer_median": med,
                                  "consecutive_fences": self._streak[r],
                                  "world": len(per_rank)})
            else:
                self._streak[r] = 0
                self._tripped.discard(r)
        if not trips:
            return None
        worst = max(trips, key=lambda t: t["value"])
        for t in trips:              # every qualifying rank latches,
            self._tripped.add(t["rank"])   # only the worst dumps
        return worst


class Watchdog:
    """Fence-point anomaly rules over the flight recorder, with
    one-shot JSONL dumps. One instance per subsystem (the engine builds
    one with ``source="train"``, the serving scheduler one with
    ``source="serving"``) — both share the process-wide recorder by
    default, so either's dump carries the full recent history."""

    def __init__(self, dump_dir, recorder=None, registry=None,
                 source="train", step_time_factor=3.0,
                 swap_stall_factor=4.0, swap_stall_min_s=0.05,
                 ttft_factor=4.0, ttft_min_s=1.0,
                 ckpt_stall_factor=4.0, ckpt_stall_min_s=0.25,
                 straggler_factor=2.0, straggler_fences=3,
                 straggler_min_s=0.0,
                 baseline_window=64, min_samples=8, check_nan=True,
                 max_dumps=0):
        self.dump_dir = dump_dir
        self.source = source
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.registry = registry if registry is not None \
            else default_registry()
        self.check_nan = bool(check_nan)
        self.max_dumps = int(max_dumps)      # 0 = unlimited
        self.dump_id = 0
        self.last_anomaly = None
        self.trips = {}                      # rule name -> count
        self._lock = threading.Lock()
        self._nan_tripped = False
        self._pool_tripped = False
        self._ckpt_corrupt_tripped = False
        self._preempt_tripped = False
        self._rank_dead_tripped = False
        self._crash_loop_tripped = False
        self._rules = {
            "step_time_outlier": RollingOutlierRule(
                "step_time_outlier", factor=step_time_factor,
                window=baseline_window, min_samples=min_samples),
            "swap_stall_outlier": RollingOutlierRule(
                "swap_stall_outlier", factor=swap_stall_factor,
                min_value=swap_stall_min_s, window=baseline_window,
                min_samples=min_samples),
            "ttft_blowup": RollingOutlierRule(
                "ttft_blowup", factor=ttft_factor, min_value=ttft_min_s,
                window=baseline_window, min_samples=min_samples),
            # ISSUE 7: the async-snapshot commit fence is supposed to be
            # ~free (writes had a whole step to land); a stall past
            # factor x baseline means the aio write stream fell behind
            # training — snapshot-stall
            "ckpt_stall_outlier": RollingOutlierRule(
                "ckpt_stall_outlier", factor=ckpt_stall_factor,
                min_value=ckpt_stall_min_s, window=baseline_window,
                min_samples=min_samples),
        }
        # ISSUE 12: per-rank straggler detection over cluster fences —
        # fed by the ClusterAggregator's rank-0 fold, never by a new
        # collective of its own
        self._straggler = StragglerRule(
            factor=straggler_factor, min_value=straggler_min_s,
            fences=straggler_fences)

    @classmethod
    def from_config(cls, watchdog_cfg, recorder=None, registry=None,
                    source="train"):
        """None when the gate is off (no ``monitor.watchdog`` block)."""
        if not getattr(watchdog_cfg, "enabled", False):
            return None
        return cls(
            watchdog_cfg.dump_dir, recorder=recorder, registry=registry,
            source=source,
            step_time_factor=watchdog_cfg.step_time_factor,
            swap_stall_factor=watchdog_cfg.swap_stall_factor,
            swap_stall_min_s=watchdog_cfg.swap_stall_min_s,
            ttft_factor=watchdog_cfg.ttft_factor,
            ttft_min_s=watchdog_cfg.ttft_min_s,
            ckpt_stall_factor=watchdog_cfg.ckpt_stall_factor,
            ckpt_stall_min_s=watchdog_cfg.ckpt_stall_min_s,
            straggler_factor=getattr(watchdog_cfg, "straggler_factor",
                                     2.0),
            straggler_fences=getattr(watchdog_cfg, "straggler_fences", 3),
            straggler_min_s=getattr(watchdog_cfg, "straggler_min_s", 0.0),
            baseline_window=watchdog_cfg.baseline_window,
            min_samples=watchdog_cfg.min_samples,
            check_nan=watchdog_cfg.check_nan,
            max_dumps=watchdog_cfg.max_dumps)

    # ------------------------------------------------------------- hooks
    # Every hook takes HOST scalars its caller already read at an
    # existing fence — the watchdog itself never syncs.

    def check_loss(self, loss_value, step=None):
        """NaN/inf loss at the steps_per_print boundary readback.
        Latched: a loss that stays non-finite dumps once; a finite loss
        re-arms."""
        if not self.check_nan:
            return None
        if math.isfinite(loss_value):
            self._nan_tripped = False
            return None
        if self._nan_tripped:
            return None
        self._nan_tripped = True
        return self._trigger("nan_loss",
                             {"loss": repr(loss_value), "step": step})

    def observe_step_time(self, step_s, step=None):
        """Boundary-window mean step time vs the rolling baseline."""
        det = self._rules["step_time_outlier"].observe(step_s)
        if det is None:
            return None
        det["step"] = step
        return self._trigger("step_time_outlier", det)

    def observe_swap_stall(self, stall_s, step=None):
        """Per-step swap-tier blocked-on-I/O seconds vs baseline (with
        an absolute floor so a 1 ms -> 5 ms wiggle never dumps)."""
        det = self._rules["swap_stall_outlier"].observe(stall_s)
        if det is None:
            return None
        det["step"] = step
        return self._trigger("swap_stall_outlier", det)

    def observe_ttft(self, ttft_s, rid=None):
        """Serving time-to-first-token vs the rolling baseline."""
        det = self._rules["ttft_blowup"].observe(ttft_s)
        if det is None:
            return None
        det["rid"] = rid
        return self._trigger("ttft_blowup", det)

    def note_pool_exhausted(self, queue_depth=0, free_pages=0,
                            need_pages=0):
        """Admission blocked on page-pool pages. Latched per episode:
        one dump until an admission succeeds (``note_pool_ok``)."""
        if self._pool_tripped:
            return None
        self._pool_tripped = True
        return self._trigger("page_pool_exhausted",
                             {"queue_depth": queue_depth,
                              "free_pages": free_pages,
                              "need_pages": need_pages})

    def note_pool_ok(self):
        self._pool_tripped = False

    def observe_ckpt_stall(self, stall_s, step=None):
        """Host seconds the engine's step boundary blocked on the
        snapshot drain fence (ISSUE 7) vs the rolling baseline, with an
        absolute floor — the snapshot-stall rule."""
        det = self._rules["ckpt_stall_outlier"].observe(stall_s)
        if det is None:
            return None
        det["step"] = step
        return self._trigger("ckpt_stall_outlier", det)

    def observe_rank_step_times(self, per_rank, step=None):
        """Cluster rank-straggler check (ISSUE 12): ``per_rank`` is the
        per-rank step-time vector the ClusterAggregator allgathered at
        an EXISTING fence (the steps_per_print readback / a snapshot
        commit fence) and folded on rank 0 — host floats only, the
        collective already happened. Trips ``rank_straggler`` naming
        the offending rank after K consecutive slow fences."""
        det = self._straggler.observe(per_rank)
        if det is None:
            return None
        det["step"] = step
        return self._trigger("rank_straggler", det)

    def note_ckpt_corrupt(self, path, reason):
        """An elastic-resume candidate failed validation (torn
        manifest, rotted shard, missing rank). Latched per recovery
        episode: a multi-candidate fallback chain dumps ONCE; a
        successful load (``note_ckpt_ok``) re-arms."""
        if self._ckpt_corrupt_tripped:
            return None
        self._ckpt_corrupt_tripped = True
        return self._trigger("ckpt_corrupt",
                             {"dir": str(path), "reason": str(reason)})

    def note_ckpt_ok(self):
        self._ckpt_corrupt_tripped = False

    def note_preempt(self, step=None, snapshotted=None, grace_s=None,
                     source=None):
        """Preemption incident (ISSUE 7): one dump carrying the ring
        history leading up to the SIGTERM, stamped with whether the
        final snapshot committed inside the grace budget."""
        if self._preempt_tripped:
            return None
        self._preempt_tripped = True
        return self._trigger("preempt",
                             {"step": step, "snapshotted": snapshotted,
                              "grace_s": grace_s, "source": source})

    def note_preempt_ok(self):
        """Re-arm the preempt latch after an incident is fully handled
        (ISSUE 11: a replica-pool supervisor survives its replicas, so
        a SECOND kill later in the same process must dump again —
        unlike training, where one preemption ends the process)."""
        self._preempt_tripped = False

    def note_rank_dead(self, rank=None, reason=None, step=None,
                       exit_code=None, blocked_s=None, deadline_s=None,
                       restart_epoch=None, world=None):
        """A rank left the world uncleanly (ISSUE 15): a hard death the
        supervisor observed (SIGKILL/OOM/node loss, ``reason``
        carrying the exit classification), or — fired from INSIDE a
        surviving rank by the collective hang watchdog
        (runtime/elastic/hang.py) — a collective stalled past the hang
        deadline (``reason="collective_hang"``, ``blocked_s``). Latched
        per incident: one dump however many ranks die together (the
        supervisor's teardown makes the survivors exit nonzero too,
        and each of those must not re-dump); a successful restart
        re-arms it (``note_world_ok``)."""
        if self._rank_dead_tripped:
            return None
        self._rank_dead_tripped = True
        return self._trigger("rank_dead",
                             {"rank": rank, "reason": reason,
                              "step": step, "exit_code": exit_code,
                              "blocked_s": blocked_s,
                              "deadline_s": deadline_s,
                              "restart_epoch": restart_epoch,
                              "world": world})

    def note_world_ok(self):
        """Re-arm the rank-dead latch after the supervisor respawned a
        healthy world — the NEXT incident is a new episode and must
        dump again."""
        self._rank_dead_tripped = False

    def note_crash_loop(self, restarts=None, max_restarts=None,
                        world=None, last_reason=None):
        """The supervisor's restart budget is exhausted (ISSUE 15): a
        world that dies every epoch stopped being restarted. Latched
        and NEVER re-armed — the condition is terminal for this
        supervisor, so there is exactly one ``crash_loop`` dump per
        process however the exit path replays."""
        if self._crash_loop_tripped:
            return None
        self._crash_loop_tripped = True
        return self._trigger("crash_loop",
                             {"restarts": restarts,
                              "max_restarts": max_restarts,
                              "world": world,
                              "last_reason": last_reason})

    # -------------------------------------------------------------- dump

    def force_dump(self, reason="manual"):
        """Unconditional dump of the current ring (debug hook)."""
        return self._trigger(reason, {}, forced=True)

    def _trigger(self, rule, detail, forced=False):
        """Write one JSONL dump of the ring: a ``dump_header`` line then
        every ring event, oldest first. Returns the dump path (None if
        dumping failed or the dump budget is spent — the trip is still
        counted and surfaced)."""
        with self._lock:
            self.dump_id += 1
            dump_id = self.dump_id
            self.trips[rule] = self.trips.get(rule, 0) + 1
        events = self.recorder.events()
        info = {"kind": "dump_header", "rule": rule, "dump_id": dump_id,
                "source": self.source, "ts": time.time(),
                "detail": detail, "n_events": len(events),
                "recorder_capacity": self.recorder.capacity,
                # ISSUE 19 satellite: which box/sha/incarnation wrote
                # this dump — the Perfetto merger and any human reading
                # a days-old dump both need it in the header, not in
                # out-of-band notes
                "provenance": dict(_provenance_doc()),
                "restart_epoch": int(
                    os.environ.get("DSTPU_RESTART_EPOCH", "0") or 0)}
        self.last_anomaly = {"rule": rule, "dump_id": dump_id,
                             "ts": info["ts"], "detail": detail}
        reg = self.registry
        reg.counter("watchdog/dumps").inc()
        reg.counter(f"watchdog/trips/{rule}").inc()
        reg.gauge("watchdog/last_dump_id").set(dump_id)
        path = None
        if not self.max_dumps or dump_id <= self.max_dumps:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir,
                    f"flight_{self.source}_{dump_id:04d}_{rule}.jsonl")
                with open(path, "w") as fh:
                    # default=repr: an exotic payload value (a tuple
                    # request id, a dtype) must degrade to its repr,
                    # never crash the fence point that triggered us
                    fh.write(json.dumps(info, default=repr) + "\n")
                    for ev in events:
                        fh.write(json.dumps(ev, default=repr) + "\n")
            except OSError as e:       # an unwritable dir must not kill
                logger.warning(f"watchdog dump failed: {e}")
                path = None
        self.last_anomaly["dump_path"] = path
        if not forced:
            logger.warning(
                f"[watchdog] {rule} tripped ({self.source}); "
                f"dump #{dump_id}: {path or '<not written>'}")
        # the anomaly marker lands in the ring AFTER the snapshot, so
        # the dump holds the pre-anomaly history and the NEXT dump shows
        # this one as an event
        self.recorder.record("anomaly", rule=rule, dump_id=dump_id,
                             dump_path=path, **{
                                 k: v for k, v in detail.items()
                                 if isinstance(v, (int, float, str,
                                                   type(None)))})
        return path

    def snapshot(self):
        """JSON-able watchdog state (serving embeds this in
        ``metrics_snapshot()``)."""
        return {"dump_id": self.dump_id,
                "last_anomaly": self.last_anomaly,
                "trips": dict(self.trips)}
