"""Live telemetry endpoint (ISSUE 12): a stdlib-only ``http.server``
thread serving the current registry state, so a long run is observable
without waiting for a watchdog dump or the next JSONL export.

Routes:

- ``/metrics`` — the Prometheus exposition dump
  (``registry.prometheus_text``) of the attached registry; on rank 0
  of a multi-process run the ``cluster/*`` gauges folded by
  telemetry/cluster.py are part of that registry, so one scrape of
  rank 0 sees the whole cluster's skew stats.
- ``/healthz`` — JSON liveness: watchdog trip summary (rule -> count,
  the last anomaly), the age of the last telemetry fence (seconds
  since the engine last folded/exchanged — a stuck run shows as a
  growing fence age long before any rule trips), and the server's own
  clock.

Everything here is pull-based and reads only host state the fences
already produced — a scrape can never force a device sync
(``test_sync_guard`` scans this module). Config: ``monitor.serve_port``
(0 = off, the default) + ``monitor.serve_host`` (127.0.0.1); the
training engine starts it on rank 0 only, ``serving.build_engine``
starts one over the serving registry when the block asks for it.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deepspeed_tpu.telemetry.registry import (default_registry,
                                              prometheus_text)
from deepspeed_tpu.utils.logging import logger


class MetricsServer:
    """One daemon http.server thread. ``port=0`` binds an ephemeral
    port (tests); the bound port is ``self.port`` after construction.
    ``fence_age_fn`` returns the wall-clock timestamp of the last
    telemetry fence (or None before the first)."""

    def __init__(self, port, registry=None, watchdog=None,
                 fence_age_fn=None, host="127.0.0.1", extra_health_fn=None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.watchdog = watchdog
        self.fence_age_fn = fence_age_fn
        self.extra_health_fn = extra_health_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):   # no stderr spam per scrape
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = prometheus_text(outer.registry).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body = json.dumps(outer.health(),
                                      default=repr).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404, "try /metrics or /healthz")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dstpu-metrics",
            daemon=True)

    def start(self):
        self._thread.start()
        return self

    def health(self):
        """The /healthz document — host state only."""
        age = None
        if self.fence_age_fn is not None:
            ts = self.fence_age_fn()
            if ts:
                age = max(time.time() - ts, 0.0)
        wd = self.watchdog
        doc = {
            "ok": True,
            "ts": time.time(),
            "last_fence_age_s": age,
            "watchdog": wd.snapshot() if wd is not None else None,
            "watchdog_trips": sum(wd.trips.values())
            if wd is not None else 0,
        }
        if self.extra_health_fn is not None:
            try:
                doc.update(self.extra_health_fn() or {})
            except Exception as e:   # a scrape must never crash the run
                doc["extra_error"] = str(e)
        return doc

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def start_metrics_server(port, **kwargs):
    """Build + start, degrading to None on a bind failure (a second
    engine in the same process racing for the same port must not kill
    training — the first one keeps serving)."""
    try:
        return MetricsServer(port, **kwargs).start()
    except OSError as e:
        logger.warning(f"telemetry /metrics endpoint unavailable "
                       f"(port {port}): {e}")
        return None
