"""Cross-rank telemetry aggregation (ISSUE 12 tentpole).

Every telemetry surface below this module is process-local: the
registry's gauges, the flight-recorder ring, the watchdog's rules. Once
a run spans real process boundaries (PR 10's gloo collectives,
``comm.hierarchy``) a straggler rank or a skewed per-link byte ledger
is invisible — each process sees only itself. This module closes that
gap under the same sync discipline as everything else in telemetry/:

- **the exchange rides existing fences only.** Each rank packs a
  fixed-size fp32 vector of its boundary metrics (window-mean step
  time, swap stall, ckpt-commit stall, loss, host RSS, per-link-class
  comm bytes) and allgathers it over the gloo process group — at the
  ``steps_per_print`` loss readback and at snapshot commit fences,
  where a host sync already exists and every rank arrives in SPMD
  lockstep. It never adds a fence of its own (``test_sync_guard``
  scans this module).
- **rank 0 folds** the ``[world, n]`` matrix into
  ``cluster/<metric>/{min,median,max,p99,argmax_rank}`` gauges plus a
  per-rank skew table (``last_table``), records a compact
  ``cluster_fence`` ring event, and feeds the per-rank step-time
  vector to the watchdog's latched ``rank_straggler`` rule
  (anomaly.StragglerRule) — the rule that names the slow rank after K
  consecutive slow fences.
- **single-process degenerates gracefully**: no collective, the local
  vector folds as a world of one, so the ``cluster/*`` gauges (and the
  /metrics endpoint that serves them) exist uniformly.

The vector layout is FIXED (``CLUSTER_METRICS``): every rank packs the
same slots in the same order, NaN meaning "not measured this fence"
(no swap tier on this rank, first window still warming). Fold stats
ignore NaNs per metric.
"""

import time

import numpy as np

from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.registry import default_registry

# one fp32 slot per metric, packed in this order on every rank
CLUSTER_METRICS = (
    "step_time_s",    # window-mean step time of the closing fold
    "swap_stall_s",   # host seconds this step blocked on swap I/O
    "ckpt_stall_s",   # last snapshot-commit fence stall
    "loss",           # the boundary loss readback
    "host_rss_mb",    # host RSS high-water mark
    "comm_intra_mb",  # fast-link (ICI-class) bytes of the last step
    "comm_inter_mb",  # slow-link (DCN-class) bytes of the last step
)

CLUSTER_STATS = ("min", "median", "max", "p99", "argmax_rank")


def cluster_metric_names():
    """Every ``cluster/*`` gauge/counter name this module can emit —
    the drift guard (tests/test_metric_names.py) checks this list
    against docs/observability.md in BOTH directions."""
    names = [f"cluster/{m}/{s}" for m in CLUSTER_METRICS
             for s in CLUSTER_STATS]
    names += ["cluster/world_size", "cluster/fences"]
    return names


def collect_local(registry=None, loss=None, overrides=None):
    """One rank's metric dict for the next fence, read from the
    registry's last observations (host scalars recorded at fences the
    caller already paid). ``overrides`` (metric -> value or None) wins
    over the registry — the engine passes its just-closed window's
    step time directly so a previous engine's history in the
    process-wide registry cannot leak in."""
    reg = registry or default_registry()
    nan = float("nan")  # sync-ok: a literal, not a readback

    # peek, don't snapshot(): a full registry snapshot summarizes (and
    # sorts the reservoir of) EVERY histogram in the process — paying
    # that per fence just to read two last-values would dwarf the
    # exchange itself once serving histograms share the registry

    def last(name):
        v = reg.peek_histogram_last(name)
        return nan if v is None else v

    def gauge(name, scale=1.0):
        v = reg.peek_gauge(name)
        return nan if v is None else v * scale

    out = {
        "step_time_s": last("train/step_time_s"),
        "swap_stall_s": last("swap/stall_s"),
        "ckpt_stall_s": last("ckpt/stall_s"),
        "loss": nan if loss is None else float(loss),  # sync-ok: the
        #                       boundary readback already produced this
        "host_rss_mb": gauge("memory/host_max_rss_mb"),
        "comm_intra_mb": gauge("comm/bytes_per_step/intra", 1 / 2**20),
        "comm_inter_mb": gauge("comm/bytes_per_step/inter", 1 / 2**20),
    }
    for k, v in (overrides or {}).items():
        out[k] = nan if v is None else float(v)  # sync-ok: host scalars
    return out


class ClusterAggregator:
    """See module docstring. One per engine; rank and world size are
    learned from the first :meth:`exchange`."""

    def __init__(self, registry=None, recorder=None, watchdog=None):
        self.registry = registry if registry is not None \
            else default_registry()
        self.recorder = recorder if recorder is not None \
            else default_recorder()
        self.watchdog = watchdog
        self.rank = 0
        self.world = 1
        self.fences = 0
        self.last_fence_ts = None     # wall clock of the last exchange
        self.last_table = None        # rank-0 per-rank skew table

    # ----------------------------------------------------------- exchange

    def exchange(self, values, step=None):
        """Allgather one fence's metric dict (see CLUSTER_METRICS) and
        fold on rank 0. MUST be called at an aligned fence on every
        rank (see utils.distributed.allgather_host_floats). Returns
        the ``[world, n]`` matrix (every rank gets it — a caller that
        wants its own skew view doesn't need to be rank 0)."""
        from deepspeed_tpu.utils.distributed import allgather_host_floats
        vec = np.asarray(  # sync-ok: host scalars packed for the fence
            [values.get(m, float("nan")) for m in CLUSTER_METRICS],
            np.float32)
        mat, rank = allgather_host_floats(vec)
        self.rank, self.world = int(rank), int(mat.shape[0])
        self.fences += 1
        # PR-12 asymmetry fix (ISSUE 19 satellite): the registry
        # counter tracks ``self.fences`` — counted here, on EVERY rank
        # per exchange — not the rank-0 fold. Before this, rank 0
        # exported N fences while every other rank exported 0, so a
        # per-rank scrape read as "ranks 1..N-1 never fence".
        self.registry.counter("cluster/fences").inc()
        self.last_fence_ts = time.time()
        if self.rank == 0:
            self._fold(mat, step)
        return mat

    def exchange_from_registry(self, registry=None, loss=None, step=None,
                               overrides=None):
        """``exchange(collect_local(...))`` — the engine's one-liner."""
        return self.exchange(
            collect_local(registry or self.registry, loss=loss,
                          overrides=overrides), step=step)

    # --------------------------------------------------------------- fold

    def _fold(self, mat, step):
        """Rank 0: per-metric cluster stats into gauges, the per-rank
        skew table, the ring breadcrumb, and the straggler rule."""
        reg = self.registry
        reg.gauge("cluster/world_size").set(self.world)
        table = {"step": step, "world": self.world, "metrics": {}}
        for i, m in enumerate(CLUSTER_METRICS):
            col = np.asarray(  # sync-ok: host matrix from the allgather
                mat[:, i], np.float64)
            finite = np.isfinite(col)
            table["metrics"][m] = [
                float(v) if ok else None  # sync-ok: host matrix entries
                for v, ok in zip(col, finite)]
            if not finite.any():
                continue
            vals = col[finite]
            reg.gauge(f"cluster/{m}/min").set(vals.min())
            reg.gauge(f"cluster/{m}/median").set(np.median(vals))
            reg.gauge(f"cluster/{m}/max").set(vals.max())
            reg.gauge(f"cluster/{m}/p99").set(np.percentile(vals, 99))
            reg.gauge(f"cluster/{m}/argmax_rank").set(
                int(np.argmax(np.where(finite, col, -np.inf))))
        self.last_table = table
        st = table["metrics"]["step_time_s"]
        self.recorder.record(
            "cluster_fence", step=step, world=self.world,
            step_time_per_rank=st,
            loss_per_rank=table["metrics"]["loss"])
        if self.watchdog is not None and any(v is not None for v in st):
            # host floats the fence already produced — the rule that
            # names a straggler rank after K consecutive slow fences
            self.watchdog.observe_rank_step_times(st, step=step)
        return table
