"""Windowed per-role SLO plane (ISSUE 19).

The autoscaling gap this closes: the `ttft_breakdown` histograms are
LIFETIME aggregates — a pool that was saturated ten minutes ago and
idle now still shows a fat p99, so nothing built on them can make a
scale decision that reacts to the last thirty seconds. This module
keeps **rolling time-bucketed windows** per (role, metric), computes
recent quantiles plus an **error-budget burn rate** against a
configured target, exports them as ``slo/*`` gauges on the existing
``/metrics`` endpoint, and distils them into the per-role scale
recommendation (:func:`roles_signal`) the ``ReplicaPool`` autoscaler
(``serving.autoscale.scale_signal: "slo"``) and the supervisor's
``roles_for_world`` ladder consume.

Wiring (all host floats, never a device sync — the plane only ever
sees values some existing fence already read back):

- the rank-0 :class:`~deepspeed_tpu.serving.transport.PrefillNode`
  feeds its own registry's TTFT segments under role ``"prefill"`` and
  every decode rank's exchanged ``MV_TICK_S`` slot under ``"decode"``
  (sampled once per aligned exchange — the same cadence the
  backpressure signals already ride);
- burn rate = (fraction of windowed samples over ``targets[metric]``)
  / ``budget``: 1.0 means violations are consuming the error budget
  exactly as fast as allowed, above ``up_burn`` the role needs
  capacity, below ``down_burn`` (every metric of the role) it has
  slack.

Stdlib-only on purpose: the drift-guard tests import this next to the
jax-free viewer chain.
"""

import threading
import time

DEFAULT_WINDOW_S = 30.0
DEFAULT_BUCKETS = 6
DEFAULT_BUDGET = 0.1        # 10% of requests may miss the target
DEFAULT_UP_BURN = 2.0
DEFAULT_DOWN_BURN = 0.25
DEFAULT_MIN_SAMPLES = 8

# the pinned (role, metric) families — tests/test_metric_names.py
# checks the exported gauge names against slo_metric_names() in BOTH
# directions, like the cluster/* and router/* namespaces
SLO_FAMILIES = (
    ("prefill", "ttft_s"),
    ("prefill", "queue_wait_s"),
    ("prefill", "transport_s"),
    ("decode", "tick_s"),
)
SLO_STATS = ("p50", "p99", "burn_rate", "samples")

DEFAULT_TARGETS = {
    "ttft_s": 1.0,
    "queue_wait_s": 0.5,
    "transport_s": 0.25,
    "tick_s": 0.1,
}


def slo_metric_names():
    """Every gauge the plane can export — the drift-guard contract."""
    names = [f"slo/{role}/{metric}/{stat}"
             for role, metric in SLO_FAMILIES for stat in SLO_STATS]
    names.append("slo/window_s")
    return names


class SloWindow:
    """Rolling time-bucketed sample store for ONE (role, metric):
    ``n_buckets`` fixed-width time buckets spanning ``window_s``
    seconds; a bucket older than the window drops whole (cheap
    eviction, no per-sample timestamps kept), and each bucket caps its
    sample count so a hot loop cannot grow the window unboundedly."""

    def __init__(self, window_s=DEFAULT_WINDOW_S,
                 n_buckets=DEFAULT_BUCKETS, per_bucket_cap=256):
        assert window_s > 0 and n_buckets >= 1
        self.window_s = float(window_s)   # sync-ok: config scalar
        self.n_buckets = int(n_buckets)
        self.bucket_s = self.window_s / self.n_buckets
        self.per_bucket_cap = int(per_bucket_cap)
        self._buckets = []        # list of [bucket_index, [values]]
        self.total = 0            # lifetime observations (not windowed)

    def _evict(self, now):
        horizon = int(now / self.bucket_s) - self.n_buckets
        self._buckets = [b for b in self._buckets if b[0] > horizon]

    def observe(self, value, now=None):
        now = time.time() if now is None else float(now)   # sync-ok: host clock
        self._evict(now)
        idx = int(now / self.bucket_s)
        self.total += 1
        if self._buckets and self._buckets[-1][0] == idx:
            vals = self._buckets[-1][1]
        else:
            vals = []
            self._buckets.append([idx, vals])
        if len(vals) < self.per_bucket_cap:
            vals.append(float(value))   # sync-ok: host scalar, plane contract

    def samples(self, now=None):
        now = time.time() if now is None else float(now)   # sync-ok: host clock
        self._evict(now)
        out = []
        for _idx, vals in self._buckets:
            out.extend(vals)
        return out


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = int(round(q / 100.0 * (len(sorted_vals) - 1)))
    return sorted_vals[min(len(sorted_vals) - 1, max(0, i))]


class SloPlane:
    """Per-(role, metric) windows + gauge export + the burn-rate math.
    Thread-safe (the serving loop feeds while a /metrics scrape
    triggers nothing — export is explicit, at tick cadence)."""

    def __init__(self, window_s=DEFAULT_WINDOW_S, targets=None,
                 budget=DEFAULT_BUDGET, up_burn=DEFAULT_UP_BURN,
                 down_burn=DEFAULT_DOWN_BURN,
                 min_samples=DEFAULT_MIN_SAMPLES,
                 n_buckets=DEFAULT_BUCKETS):
        self.window_s = float(window_s)   # sync-ok: config scalar
        self.n_buckets = int(n_buckets)
        self.targets = dict(DEFAULT_TARGETS)
        if targets:
            self.targets.update({str(k): float(v)   # sync-ok: config
                                 for k, v in targets.items()})
        self.budget = max(float(budget), 1e-6)   # sync-ok: config scalar
        self.up_burn = float(up_burn)   # sync-ok: config scalar
        self.down_burn = float(down_burn)   # sync-ok: config scalar
        self.min_samples = int(min_samples)
        self._windows = {}        # (role, metric) -> SloWindow
        self._fed_counts = {}     # (role, metric) -> histogram count seen
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, slo_cfg):
        """Build from a parsed ``config.SloConfig`` (None when the
        block disabled it)."""
        if slo_cfg is None or not getattr(slo_cfg, "enabled", False):
            return None
        return cls(window_s=slo_cfg.window_s, targets=slo_cfg.targets,
                   budget=slo_cfg.budget, up_burn=slo_cfg.up_burn,
                   down_burn=slo_cfg.down_burn,
                   min_samples=slo_cfg.min_samples)

    def _window(self, role, metric):
        key = (str(role), str(metric))
        w = self._windows.get(key)
        if w is None:
            w = self._windows[key] = SloWindow(
                self.window_s, self.n_buckets)
        return w

    def observe(self, role, metric, value, now=None):
        with self._lock:
            self._window(role, metric).observe(value, now=now)

    def feed_counted(self, role, metric, values, count, now=None,
                     source=None):
        """Feed only the NEW tail of a registry histogram: ``values``
        is the bounded reservoir, ``count`` its lifetime count. The
        caller polls at tick cadence; this dedupes so a quiet tick
        re-feeds nothing (a windowed quantile fed the same TTFT every
        tick would freeze the window at the last request). ``source``
        disambiguates when several histograms feed one window (the
        transport segments) — each keeps its own count cursor."""
        key = (str(role), str(metric), str(source or metric))
        with self._lock:
            seen = self._fed_counts.get(key, 0)
            fresh = int(count) - seen
            if fresh <= 0:
                return
            self._fed_counts[key] = int(count)
            w = self._window(role, metric)
            for v in values[-min(fresh, len(values)):]:
                w.observe(v, now=now)

    def stats(self, role, metric, now=None):
        """``{p50, p99, burn_rate, samples}`` of the current window
        (None when it holds no samples)."""
        with self._lock:
            key = (str(role), str(metric))
            w = self._windows.get(key)
            if w is None:
                return None
            vals = sorted(w.samples(now=now))
        if not vals:
            return None
        target = self.targets.get(str(metric))
        burn = 0.0
        if target is not None:
            viol = sum(1 for v in vals if v > target)
            burn = (viol / len(vals)) / self.budget
        return {"p50": _pct(vals, 50), "p99": _pct(vals, 99),
                "burn_rate": burn, "samples": len(vals)}

    def export(self, registry, now=None):
        """Set the ``slo/*`` gauges for every family that has windowed
        samples (families with no samples export nothing — same
        no-phantom-metrics discipline as the registry peeks)."""
        registry.gauge("slo/window_s").set(self.window_s)
        for role, metric in SLO_FAMILIES:
            s = self.stats(role, metric, now=now)
            if s is None:
                continue
            for stat in SLO_STATS:
                registry.gauge(f"slo/{role}/{metric}/{stat}").set(
                    s[stat])

    def recommend(self, now=None):
        """Direct (registry-free) form of :func:`roles_signal`."""
        out = {}
        for role in {r for r, _m in SLO_FAMILIES}:
            burns = []
            for r, metric in SLO_FAMILIES:
                if r != role:
                    continue
                s = self.stats(role, metric, now=now)
                if s is not None and s["samples"] >= self.min_samples:
                    burns.append(s["burn_rate"])
            out[role] = _decide(burns, self.up_burn, self.down_burn)
        return out


def _decide(burns, up_burn, down_burn):
    if not burns:
        return "hold"
    if max(burns) >= up_burn:
        return "up"
    if max(burns) <= down_burn:
        return "down"
    return "hold"


def roles_signal(registry, up_burn=DEFAULT_UP_BURN,
                 down_burn=DEFAULT_DOWN_BURN,
                 min_samples=DEFAULT_MIN_SAMPLES):
    """The per-role scale recommendation, derived PURELY from the
    exported ``slo/*`` gauges of ``registry`` — the consumer contract:
    an autoscaler (ReplicaPool, the supervisor ladder, an external
    operator scraping /metrics) needs no access to the plane object,
    only to the gauge plane it exported. Returns
    ``{"prefill"|"decode": "up"|"down"|"hold"}``; a role with no
    exported families (or too few windowed samples) holds."""
    out = {}
    for role in sorted({r for r, _m in SLO_FAMILIES}):
        burns = []
        for r, metric in SLO_FAMILIES:
            if r != role:
                continue
            burn = registry.peek_gauge(f"slo/{role}/{metric}/burn_rate")
            n = registry.peek_gauge(f"slo/{role}/{metric}/samples")
            if burn is None or n is None or n < min_samples:
                continue
            burns.append(float(burn))   # sync-ok: gauge peek, host value
        out[role] = _decide(burns, up_burn, down_burn)
    return out
