"""Unified telemetry: per-step metrics registry, async-safe spans and a
config-gated programmatic XLA trace window.

The reference DeepSpeed treats observability as a first-class subsystem
(TensorBoard scalars + wall-clock breakdown timers + the FLOPS profiler
wired into the engine loop); this package is the TPU rebuild of that
layer, with one discipline the reference's CUDA timers didn't need:
**nothing here forces a device sync in a hot loop**. Under jit the
dispatch is asynchronous, so spans record host wall time + a profiler
annotation only, and device-accurate accounting happens (a) at
``steps_per_print`` boundaries, where the engine's existing loss
readback is the fence, or (b) inside an XLA trace window where the
profiler timeline is the source of truth.

Layout:

- ``registry``: process-wide counters / gauges / histograms with
  snapshot/reset, plus three exporters — JSONL stream,
  ``SummaryEventWriter`` bridge, Prometheus text dump;
- ``spans``: ``span("tag")`` host-side context manager
  (``jax.profiler.TraceAnnotation`` + wall time), ``annotate("tag")``
  for trace-time ``jax.named_scope`` labels inside jitted train fns,
  and ``TraceWindow`` wrapping ``jax.profiler.start_trace/stop_trace``
  around a configured step range;
- ``recorder``: the flight recorder — a process-wide bounded ring of
  structured events (step/swap/serving lifecycle) for post-anomaly
  reconstruction (ISSUE 6);
- ``anomaly``: the watchdog — fence-point anomaly rules (NaN loss,
  step-time / swap-stall outliers, TTFT blowup, page-pool exhaustion)
  that write one-shot JSONL dumps of the ring;
- ``view``: ``python -m deepspeed_tpu.telemetry.view <dump.jsonl>``
  renders a dump as per-step phase tables + per-request timelines.
"""

from deepspeed_tpu.telemetry.registry import (     # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, default_registry,
    JsonlExporter, SummaryBridge, prometheus_text, record_comm_exposure)
from deepspeed_tpu.telemetry.spans import (        # noqa: F401
    span, annotate, TraceWindow)
from deepspeed_tpu.telemetry.recorder import (     # noqa: F401
    FlightRecorder, default_recorder)
from deepspeed_tpu.telemetry.anomaly import Watchdog  # noqa: F401
