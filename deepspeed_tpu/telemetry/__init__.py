"""Unified telemetry: per-step metrics registry, async-safe spans and a
config-gated programmatic XLA trace window.

The reference DeepSpeed treats observability as a first-class subsystem
(TensorBoard scalars + wall-clock breakdown timers + the FLOPS profiler
wired into the engine loop); this package is the TPU rebuild of that
layer, with one discipline the reference's CUDA timers didn't need:
**nothing here forces a device sync in a hot loop**. Under jit the
dispatch is asynchronous, so spans record host wall time + a profiler
annotation only, and device-accurate accounting happens (a) at
``steps_per_print`` boundaries, where the engine's existing loss
readback is the fence, or (b) inside an XLA trace window where the
profiler timeline is the source of truth.

Layout:

- ``registry``: process-wide counters / gauges / histograms with
  snapshot/reset, plus three exporters — JSONL stream,
  ``SummaryEventWriter`` bridge, Prometheus text dump;
- ``spans``: ``span("tag")`` host-side context manager
  (``jax.profiler.TraceAnnotation`` + wall time), ``annotate("tag")``
  for trace-time ``jax.named_scope`` labels inside jitted train fns,
  and ``TraceWindow`` wrapping ``jax.profiler.start_trace/stop_trace``
  around a configured step range;
- ``recorder``: the flight recorder — a process-wide bounded ring of
  structured events (step/swap/serving lifecycle) for post-anomaly
  reconstruction (ISSUE 6);
- ``anomaly``: the watchdog — fence-point anomaly rules (NaN loss,
  step-time / swap-stall outliers, TTFT blowup, page-pool exhaustion)
  that write one-shot JSONL dumps of the ring;
- ``view``: ``python -m deepspeed_tpu.telemetry.view <dump.jsonl>``
  renders a dump as per-step phase tables + per-request timelines;
- ``cluster``: cross-rank aggregation (ISSUE 12) — a fixed fp32
  metrics vector allgathered at existing fences, folded on rank 0
  into ``cluster/*`` skew gauges + the ``rank_straggler`` rule;
- ``serve``: the live ``/metrics`` + ``/healthz`` http endpoint
  (``monitor.serve_port``), stdlib http.server in a daemon thread;
- ``slo``: the windowed per-role SLO plane (ISSUE 19) — rolling
  quantiles + error-budget burn rate per (role, metric), exported as
  ``slo/*`` gauges and distilled into the per-role scale
  recommendation autoscalers consume;
- ``perfetto``: Chrome trace-event export (ISSUE 19) — N per-rank
  dumps merged into one ``ui.perfetto.dev`` timeline with causal
  span ids and handoff flow arrows (``view --format perfetto``).
"""

from deepspeed_tpu.telemetry.registry import (     # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, default_registry,
    JsonlExporter, SummaryBridge, prometheus_text, record_comm_exposure)
from deepspeed_tpu.telemetry.spans import (        # noqa: F401
    span, annotate, TraceWindow)
from deepspeed_tpu.telemetry.recorder import (     # noqa: F401
    FlightRecorder, default_recorder)
from deepspeed_tpu.telemetry.anomaly import Watchdog  # noqa: F401

# cluster/serve resolve lazily (PEP 562, same trick as the package
# root): cluster.py imports numpy at module level, and the dump
# viewer's "pure stdlib, runs anywhere" contract covers machines
# without numpy too — an eager import here would put numpy on
# `python -m deepspeed_tpu.telemetry.view`'s import chain
# (tests/test_metric_names.py poisons BOTH jax and numpy to pin this).
_LAZY_ATTRS = {
    "ClusterAggregator": ("deepspeed_tpu.telemetry.cluster",
                          "ClusterAggregator"),
    "CLUSTER_METRICS": ("deepspeed_tpu.telemetry.cluster",
                        "CLUSTER_METRICS"),
    "cluster_metric_names": ("deepspeed_tpu.telemetry.cluster",
                             "cluster_metric_names"),
    "cluster": ("deepspeed_tpu.telemetry.cluster", None),
    "MetricsServer": ("deepspeed_tpu.telemetry.serve", "MetricsServer"),
    "start_metrics_server": ("deepspeed_tpu.telemetry.serve",
                             "start_metrics_server"),
    "serve": ("deepspeed_tpu.telemetry.serve", None),
    # stdlib-only modules, lazy anyway so `import deepspeed_tpu.
    # telemetry` stays exactly as cheap as before ISSUE 19
    "SloPlane": ("deepspeed_tpu.telemetry.slo", "SloPlane"),
    "slo_metric_names": ("deepspeed_tpu.telemetry.slo",
                         "slo_metric_names"),
    "roles_signal": ("deepspeed_tpu.telemetry.slo", "roles_signal"),
    "slo": ("deepspeed_tpu.telemetry.slo", None),
    "perfetto": ("deepspeed_tpu.telemetry.perfetto", None),
}

from deepspeed_tpu.utils.lazy import lazy_attrs  # noqa: E402

__getattr__, __dir__ = lazy_attrs(__name__, _LAZY_ATTRS)
