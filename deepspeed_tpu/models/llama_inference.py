"""LLaMA fused serving — the GPT-2 fast-decode stack for the
RMSNorm + split-qkv/GQA + SwiGLU family.

Reference role: the reference applies its fused inference kernels +
int8 quantization across client architectures via module injection
(deepspeed/module_inject/replace_module.py:8, module_quantize.py). Here
the family-specific pieces are STATIC FLAGS on the same stacked Pallas
kernels GPT-2 serves through (ops/pallas/decode.py): ``norm='rms'``
turns the fused norm into RMSNorm and drops every bias operand,
``act='swiglu'`` streams the gate and up tiles together, and the
cached-attention kernel takes R = H/Hkv grouped query rows per KV head
so the GQA cache is read once per token at its reduced head count.

Layout: serving params are PACKED stacks —

    qkv_w [L, E, (H + 2*Hkv) * D]   (q | k | v column blocks)
    o_w   [L, H*D, E]   gate_w/up_w [L, E, F]   down_w [L, F, E]
    norm1/norm2 [L, E]; embed [V, E]; head [V, E]; norm_scale [E]

optionally int8 (kernel_q + per-tensor-per-layer scale). The prompt
pass runs on the SAME packed (de-quantized on the fly) stacks — the
original flax tree never has to coexist with the packed one in HBM,
which is what lets a 7B model serve quantized on a 16 GB chip.
"""

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import (LlamaConfig, rope_angles,
                                        apply_rope)


_STEP_CACHE = {}


# ------------------------------------------------------------- packing

def convert_llama_serving_params(params, cfg: LlamaConfig):
    """LlamaForCausalLM (scan-stacked) params → packed serving tree."""
    assert cfg.scan_layers, "serving packs the scan-stacked layout"
    blk = params["layers"]["blk"]
    qkv = jnp.concatenate([blk["attn"]["q_proj"]["kernel"],
                           blk["attn"]["k_proj"]["kernel"],
                           blk["attn"]["v_proj"]["kernel"]], axis=-1)
    return {
        "embed": params["embed_tokens"],
        "head": params["lm_head"],
        "norm_scale": params["norm"]["scale"],
        "blk": {
            "qkv_w": {"kernel": qkv},
            "o_w": {"kernel": blk["attn"]["o_proj"]["kernel"]},
            "gate_w": {"kernel": blk["mlp"]["gate_proj"]["kernel"]},
            "up_w": {"kernel": blk["mlp"]["up_proj"]["kernel"]},
            "down_w": {"kernel": blk["mlp"]["down_proj"]["kernel"]},
            "norm1": blk["input_norm"]["scale"],
            "norm2": blk["post_attn_norm"]["scale"],
        },
    }


def quantize_llama_serving_params(sparams):
    """Packed serving tree → int8 storage (kernel_q int8 + kernel_scale
    [L] fp32 per-tensor-per-layer symmetric scales). Embeddings, head
    and norms stay full precision (matching the GPT-2 int8 recipe)."""
    out = {k: v for k, v in sparams.items() if k != "blk"}
    blk = {}
    for name, sub in sparams["blk"].items():
        if not (isinstance(sub, dict) and "kernel" in sub):
            blk[name] = sub
            continue
        w = jnp.asarray(sub["kernel"])
        L = w.shape[0]
        flat = w.reshape(L, -1).astype(jnp.float32)
        amax = jnp.max(jnp.abs(flat), axis=1, keepdims=True)
        scale = jnp.maximum(amax / 127.0, 1e-12)
        q = jnp.clip(jnp.round(flat / scale), -127, 127)
        blk[name] = {"kernel_q": q.astype(jnp.int8).reshape(w.shape),
                     "kernel_scale": scale.reshape(L)}
    out["blk"] = blk
    return out


def random_int8_serving_params(cfg: LlamaConfig, seed=0):
    """Random int8 packed serving tree — bench/verify harnesses read
    exactly the bytes a converted checkpoint would without
    materializing the bf16 model first (13.5 GB at 7B)."""
    rs = np.random.RandomState(seed)
    E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                    cfg.head_dim)
    F, L, V = cfg.intermediate_size, cfg.n_layers, cfg.vocab_size

    def q8(shape):
        return {"kernel_q": jnp.asarray(
            rs.randint(-80, 80, size=shape), jnp.int8),
            "kernel_scale": jnp.full((shape[0],), 2e-3, jnp.float32)}

    return {
        "embed": jnp.asarray(rs.randn(V, E) * 0.01, jnp.bfloat16),
        "head": jnp.asarray(rs.randn(V, E) * 0.01, jnp.bfloat16),
        "norm_scale": jnp.ones((E,), jnp.float32),
        "blk": {
            "qkv_w": q8((L, E, (H + 2 * Hkv) * D)),
            "o_w": q8((L, H * D, E)),
            "gate_w": q8((L, E, F)),
            "up_w": q8((L, E, F)),
            "down_w": q8((L, F, E)),
            "norm1": jnp.ones((L, E), jnp.float32),
            "norm2": jnp.ones((L, E), jnp.float32),
        },
    }


def _weights(blk, name, Lyr):
    """(stack, scale_vec) for either storage."""
    sub = blk[name]
    if "kernel_q" in sub:
        return sub["kernel_q"], sub["kernel_scale"].reshape(Lyr)
    return sub["kernel"], jnp.ones((Lyr,), jnp.float32)


def _rms_x(x, w, eps):
    from deepspeed_tpu.ops.pallas.decode import _rms
    return _rms(x, w, eps).astype(x.dtype)


def _rope_one(x, pos, theta):
    """RoPE on [B, Hx, D] rows at a single (traced) position."""
    B, H, D = x.shape
    cos, sin = rope_angles(pos.reshape(1), D, theta)   # [1, D//2]
    return apply_rope(x[:, :, None, :], cos, sin).reshape(B, H, D)


# ------------------------------------------------------------- fast loop

def _supports_fast_decode(cfg: LlamaConfig, B, quantize_bits,
                          kv_cache_bits):
    """D < 128 is fine as long as every PACKED projection width is
    lane-aligned — the kernels tile the packed columns, not heads."""
    E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                    cfg.head_dim)
    return (quantize_bits in (0, 8) and kv_cache_bits in (0, 8)
            and B <= 64 and cfg.scan_layers and E % 128 == 0
            and ((H + 2 * Hkv) * D) % 128 == 0 and (H * D) % 128 == 0
            and cfg.intermediate_size % 128 == 0)


def _fast_fns(cfg: LlamaConfig, max_out: int, weights_q8: bool,
              cache_q8: bool):
    """(prompt, decode) jitted once per (config, cache length, storage).

    The prompt pass runs on the packed stacks (dequantizing per layer in
    XLA — a one-time ~bandwidth cost) and fills the caches directly in
    their serving storage; the decode loop is the stacked-kernel manual
    scan, one compiled program for all new tokens."""
    key = (cfg, max_out, weights_q8, cache_q8)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]
    from deepspeed_tpu.ops.pallas.decode import (
        ln_qkv_int8_stacked, kv_quant_int8, decode_attention_int8_stacked,
        decode_attention_fp_stacked, out_ffn_int8_stacked,
        matvec_int8_stacked)
    E, H, Hkv, D = (cfg.hidden_size, cfg.n_heads, cfg.kv_heads,
                    cfg.head_dim)
    F, Lyr = cfg.intermediate_size, cfg.n_layers
    rep = H // Hkv
    eps = cfg.rms_eps
    L_cache = max_out

    def deq(stack, scale, l):
        w = stack[l]
        if stack.dtype == jnp.int8:
            return (w.astype(jnp.float32) * scale[l]).astype(cfg.dtype)
        return w.astype(cfg.dtype)

    @functools.partial(jax.jit, donate_argnums=())
    def prompt(p, ids):
        from deepspeed_tpu.ops.attention import dot_product_attention
        blk = p["blk"]
        B, S = ids.shape
        # pad to a flash-tileable length: an arbitrary prompt length
        # (e.g. 1968) divides none of the flash block sizes, and the
        # reference fallback materializes [B, H, S, S] fp32 scores —
        # 3.8 GB at 7B/b8 (the r5 OOM). Causal masking makes the tail
        # padding inert for every real position.
        Sp = -(-S // 128) * 128
        x = p["embed"][ids].astype(cfg.dtype)
        if Sp != S:
            x = jnp.pad(x, [(0, 0), (0, Sp - S), (0, 0)])
        positions = jnp.arange(Sp)
        cos, sin = rope_angles(positions, D, cfg.rope_theta)
        Wq, sq = _weights(blk, "qkv_w", Lyr)
        Wo, so = _weights(blk, "o_w", Lyr)
        Wg, sg = _weights(blk, "gate_w", Lyr)
        Wu, su = _weights(blk, "up_w", Lyr)
        Wd, sd = _weights(blk, "down_w", Lyr)

        def quant_rows(t):
            # per-(b, head, pos) symmetric int8 — INSIDE the layer scan
            # so the fp32 transient is one layer's K or V (~MBs), not
            # the whole stacked cache (4.3 GB at 7B/2k — the r5 OOM)
            tf = t.astype(jnp.float32)
            sc = jnp.maximum(jnp.max(jnp.abs(tf), axis=-1) / 127.0,
                             1e-12)
            codes = jnp.clip(jnp.round(tf / sc[..., None]),
                             -127, 127).astype(jnp.int8)
            return codes, sc

        def layer(x, l):
            u = _rms_x(x, blk["norm1"][l], eps)
            qkv = u @ deq(Wq, sq, l)
            q = qkv[..., :H * D].reshape(B, Sp, H, D) \
                .transpose(0, 2, 1, 3)
            k = qkv[..., H * D:(H + Hkv) * D] \
                .reshape(B, Sp, Hkv, D).transpose(0, 2, 1, 3)
            v = qkv[..., (H + Hkv) * D:] \
                .reshape(B, Sp, Hkv, D).transpose(0, 2, 1, 3)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            ctx = dot_product_attention(q, k, v, causal=True)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, Sp, H * D)
            x = x + ctx @ deq(Wo, so, l)
            u2 = _rms_x(x, blk["norm2"][l], eps)
            h = jax.nn.silu(u2 @ deq(Wg, sg, l)) * (u2 @ deq(Wu, su, l))
            x = x + h @ deq(Wd, sd, l)
            if cache_q8:
                kcod, ksc = quant_rows(k)
                vcod, vsc = quant_rows(v)
                return x, (kcod, ksc, vcod, vsc)
            return x, (k.astype(cfg.dtype), v.astype(cfg.dtype))

        x, ys = jax.lax.scan(layer, x, jnp.arange(Lyr))

        def to_cache(t):
            # drop the pad tail, keep the first S real rows, pad to the
            # cache length (position axis is 3)
            t = t[:, :, :, :S]
            pad = [(0, 0)] * t.ndim
            pad[3] = (0, L_cache - S)
            return jnp.pad(t, pad)

        if cache_q8:
            kcod, ksc, vcod, vsc = ys       # scales [Lyr, B, Hkv, Sp]
            caches = (to_cache(kcod),
                      to_cache(ksc).reshape(Lyr, B, Hkv, 1, L_cache),
                      to_cache(vcod),
                      to_cache(vsc).reshape(Lyr, B, Hkv, 1, L_cache))
        else:
            ks, vs = ys
            caches = (to_cache(ks), to_cache(vs))
        logits = jnp.einsum(
            "be,ve->bv", _rms_x(x[:, S - 1], p["norm_scale"], eps),
            p["head"].astype(cfg.dtype))
        return logits, caches

    @functools.partial(jax.jit, static_argnums=(4,), donate_argnums=(2,))
    def fast_scan(p, blk, caches, first_tok, steps, start, rngs,
                  temperature):
        embed = p["embed"].astype(cfg.dtype)
        head = p["head"].astype(cfg.dtype)
        norm_scale = p["norm_scale"]
        Wq, sq = _weights(blk, "qkv_w", Lyr)
        Wo, so = _weights(blk, "o_w", Lyr)
        Wg, sg = _weights(blk, "gate_w", Lyr)
        Wu, su = _weights(blk, "up_w", Lyr)
        Wd, sd = _weights(blk, "down_w", Lyr)
        n1 = blk["norm1"].reshape(Lyr, 1, E)
        n2 = blk["norm2"].reshape(Lyr, 1, E)
        B = first_tok.shape[0]

        def tick(carry, r):
            caches, tok, offset = carry
            x = embed[tok]                            # [B, E]
            x = jnp.where(offset >= L_cache,
                          jnp.float32(jnp.nan).astype(x.dtype), x)

            def layer(car, l):
                x, caches = car
                qkv = ln_qkv_int8_stacked(x, n1, None, Wq, sq, None, l,
                                          eps=eps, norm="rms")
                q3 = qkv[:, :H * D].reshape(B, H, D)
                k3 = qkv[:, H * D:(H + Hkv) * D].reshape(B, Hkv, D)
                v3 = qkv[:, (H + Hkv) * D:].reshape(B, Hkv, D)
                q3 = _rope_one(q3, offset, cfg.rope_theta)
                k3 = _rope_one(k3, offset, cfg.rope_theta)
                qg = q3.reshape(B, Hkv, rep, D)
                dus = jax.lax.dynamic_update_slice
                if cache_q8:
                    kc, ks, vc, vs = caches
                    kq8, ksc, vq8, vsc = kv_quant_int8(k3, v3)
                    kc = dus(kc, kq8[None, :, :, None, :],
                             (l, 0, 0, offset, 0))
                    vc = dus(vc, vq8[None, :, :, None, :],
                             (l, 0, 0, offset, 0))
                    ks = dus(ks, ksc.reshape(1, B, Hkv, 1, 1),
                             (l, 0, 0, 0, offset))
                    vs = dus(vs, vsc.reshape(1, B, Hkv, 1, 1),
                             (l, 0, 0, 0, offset))
                    ctx = decode_attention_int8_stacked(
                        qg, kc, ks, vc, vs, offset, l,
                        scale=1.0 / np.sqrt(D))
                    caches = (kc, ks, vc, vs)
                else:
                    kc, vc = caches
                    kc = dus(kc, k3[None, :, :, None, :].astype(kc.dtype),
                             (l, 0, 0, offset, 0))
                    vc = dus(vc, v3[None, :, :, None, :].astype(vc.dtype),
                             (l, 0, 0, offset, 0))
                    ctx = decode_attention_fp_stacked(
                        qg, kc, vc, offset, l, scale=1.0 / np.sqrt(D))
                    caches = (kc, vc)
                ctx2 = ctx.reshape(B, H * D)
                # whole-[E,E] o_proj blocks blow scoped VMEM past
                # E~2048; split it onto the tiled stacked matvec there
                if E * E * Wo.dtype.itemsize <= (6 << 20):
                    x = out_ffn_int8_stacked(
                        ctx2, x, Wo, so, None, n2, None, Wg, sg, None,
                        Wd, sd, None, l, act="swiglu", eps=eps,
                        norm="rms", w1b_stack=Wu, s1b=su)
                else:
                    x1 = x + matvec_int8_stacked(ctx2, Wo, so, l)
                    x = out_ffn_int8_stacked(
                        None, x1, None, None, None, n2, None, Wg, sg,
                        None, Wd, sd, None, l, act="swiglu", eps=eps,
                        norm="rms", w1b_stack=Wu, s1b=su,
                        fuse_proj=False)
                return (x, caches), None

            (x, caches), _ = jax.lax.scan(
                layer, (x, caches), jnp.arange(Lyr, dtype=jnp.int32))
            logits = jnp.einsum("be,ve->bv",
                                _rms_x(x, norm_scale, eps), head)
            nxt = jax.lax.cond(
                temperature > 0,
                lambda: jax.random.categorical(
                    r, logits.astype(jnp.float32)
                    / jnp.maximum(temperature, 1e-6), axis=-1),
                lambda: jnp.argmax(logits, axis=-1))
            return (caches, nxt, offset + 1), tok

        (caches, last, _), toks = jax.lax.scan(
            tick, (caches, first_tok, start), rngs, length=steps)
        return (jnp.concatenate([toks.transpose(1, 0), last[:, None]],
                                axis=1), caches)

    _STEP_CACHE[key] = (prompt, fast_scan)
    return _STEP_CACHE[key]


def llama_fast_generate(cfg: LlamaConfig, sparams, input_ids,
                        max_new_tokens=20, temperature: float = 0.0,
                        rng=None, max_out_tokens: int = 0,
                        kv_cache_bits: int = 0):
    """Fused-kernel generation over PACKED serving params (see
    convert_llama_serving_params / quantize_llama_serving_params).
    Same contract as models.gpt2_inference.generate; the whole decode
    loop is one compiled program over the stacked kernels."""
    input_ids = jnp.asarray(input_ids)
    if max_new_tokens <= 0:
        return input_ids
    B, S = input_ids.shape
    total = S + max_new_tokens
    max_out = max_out_tokens or cfg.max_seq_len
    assert total <= max_out, (total, max_out)
    weights_q8 = "kernel_q" in sparams["blk"]["qkv_w"]
    if not _supports_fast_decode(cfg, B, 8 if weights_q8 else 0,
                                 kv_cache_bits):
        raise ValueError(
            f"config outside the fused fast-decode envelope (B={B}, "
            f"E={cfg.hidden_size}, packed qkv width "
            f"{(cfg.n_heads + 2 * cfg.kv_heads) * cfg.head_dim}, "
            f"F={cfg.intermediate_size}, scan_layers={cfg.scan_layers}) "
            "— see _supports_fast_decode; serve via models.llama."
            "llama_generate (unpacked flax path) instead")
    prompt, fast_scan = _fast_fns(cfg, max_out, weights_q8,
                                  kv_cache_bits == 8)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    logits, caches = prompt(sparams, input_ids)
    rng, sub = jax.random.split(rng)
    if temperature and temperature > 0:
        first = jax.random.categorical(
            sub, logits.astype(jnp.float32) / temperature, axis=-1)
    else:
        first = jnp.argmax(logits, axis=-1)
    if max_new_tokens <= 1:
        return jnp.concatenate([input_ids, first[:, None]], axis=1)
    new, _ = fast_scan(
        {k: v for k, v in sparams.items() if k != "blk"},
        sparams["blk"], caches, first, max_new_tokens - 1,
        jnp.asarray(S, jnp.int32),
        jax.random.split(rng, max_new_tokens - 1),
        jnp.float32(temperature or 0.0))
    return jnp.concatenate([input_ids, new], axis=1)
