"""LLaMA family — RoPE + RMSNorm + SwiGLU + grouped-query attention,
TPU-first.

The reference serves/trains LLaMA through HF + module injection
(deepspeed/module_inject/containers/llama.py); here the family is
in-tree flax with the same TPU design as the GPT-2 flagship
(models/gpt2.py): bf16 activations over fp32 masters, `nn.scan` layers,
remat with the SAME named-residual policies ("qkv"/"attn_proj"/
"mlp_fc"/"mlp_proj" + the flash kernel's "flash_o"/"flash_lse" — so
every GPT2Config remat_policy string works unchanged), Pallas flash
attention, fused chunked head+loss, and sequence parallelism over a
live mesh seq axis (ring or Ulysses).

GQA: ``n_kv_heads < n_heads`` stores/computes K/V (and their decode
caches) at the reduced head count; the projections, optimizer state and
cache memory all shrink by H/Hkv. The flash FORWARD (training and
prefill) consumes the reduced-head K/V directly — Hkv-aware block index
maps fold each query head onto its KV head, so full-head K/V is never
materialized in HBM on the forward path. The flash BACKWARD still
repeats K/V transiently (bwd-only) and sums dk/dv over the rep query
heads; a dk/dv-accumulating GQA backward kernel is the remaining
optimization. SP backends (ring/Ulysses) rotate K/V at full head count.
"""

import dataclasses
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.ops.attention import dot_product_attention
from deepspeed_tpu.models.gpt2 import (_embed_lookup, _remat_policy,
                                       chunked_lm_loss, lm_loss)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 0              # 0 → MHA (= n_heads); <n_heads → GQA
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: Optional[str] = None
    scan_layers: bool = True
    scan_unroll: int = 1
    sp_backend: str = "ring"         # mesh seq-axis attention backend
    use_flash: Optional[bool] = None
    loss_chunk: int = 0              # fused chunked head+loss (see gpt2)

    @property
    def kv_heads(self):
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self):
        return self.hidden_size // self.n_heads

    def num_params(self):
        E, F, L, V = (self.hidden_size, self.intermediate_size,
                      self.n_layers, self.vocab_size)
        Dkv = self.kv_heads * self.head_dim
        per_layer = E * E + 2 * E * Dkv + E * E + 3 * E * F + 2 * E
        return 2 * V * E + L * per_layer + E


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("scale", nn.initializers.ones,
                       (x.shape[-1],), self.param_dtype)
        xf = x.astype(jnp.float32)
        n = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                               + self.eps)
        return (n * w.astype(jnp.float32)).astype(self.dtype)


def rope_angles(positions, head_dim, theta):
    """[S] positions → (cos, sin) [S, head_dim//2] fp32."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                      dtype=jnp.float32) / head_dim))
    ang = positions.astype(jnp.float32)[:, None] * inv[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """Rotary embedding on [B, H, S, D] (split-halves convention — the
    same rotation HF's LLaMA applies; conversion from the interleaved
    convention is folded into weight import)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[None, None].astype(x.dtype)
    s = sin[None, None].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class LlamaAttention(nn.Module):
    config: LlamaConfig
    max_out_tokens: int = 0      # >0 → serving mode with a KV cache

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        B, S, E = x.shape
        H, Hkv, D = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02), name=name)
        q = dense(H * D, "q_proj")(x)
        k = dense(Hkv * D, "k_proj")(x)
        v = dense(Hkv * D, "v_proj")(x)
        # all three projections carry the 'qkv' tag so every GPT2Config
        # remat_policy string (which saves 'qkv' residuals) works
        # unchanged on this model
        q = checkpoint_name(q, "qkv")
        k = checkpoint_name(k, "qkv")
        v = checkpoint_name(v, "qkv")
        qh = q.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        kh = k.reshape(B, S, Hkv, D).transpose(0, 2, 1, 3)
        vh = v.reshape(B, S, Hkv, D).transpose(0, 2, 1, 3)
        cos, sin = rope_angles(positions, D, cfg.rope_theta)
        qh = apply_rope(qh, cos, sin)
        kh = apply_rope(kh, cos, sin)

        use_cache = self.max_out_tokens > 0 and (
            self.has_variable("cache", "cached_key")
            or self.is_mutable_collection("cache"))
        if use_cache:
            # serving: append RoPE'd K/V to the head-major cache and
            # attend to the filled prefix (same layout/overflow contract
            # as the fused GPT-2 stack, ops/transformer/inference.py)
            L = self.max_out_tokens
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (B, Hkv, L, D), kh.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (B, Hkv, L, D), vh.dtype)
            idx = self.variable("cache", "cache_index",
                                lambda: jnp.zeros((), jnp.int32))
            start = idx.value
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, kh, (0, 0, start, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, vh, (0, 0, start, 0))
            idx.value = start + S
            overflow = (start + S) > L
            qh = jnp.where(overflow,
                           jnp.float32(jnp.nan).astype(qh.dtype), qh)
            # GQA without materializing a repeated cache: fold the
            # rep = H/Hkv query heads sharing each KV head into the
            # contraction's row dim (q heads are grouped consecutively
            # per KV head, so this is a pure reshape) — the decode loop
            # reads the Hkv-head cache directly instead of rep x the
            # bytes every token
            rep = H // Hkv
            qg = qh.reshape(B, Hkv, rep * S, D)
            q_pos = start + jnp.arange(S)[:, None]
            visible = jnp.arange(L)[None, :] <= q_pos        # [S, L]
            vis_g = jnp.broadcast_to(visible[None],
                                     (rep, S, L)).reshape(rep * S, L)
            dn_qk = (((3,), (3,)), ((0, 1), (0, 1)))
            scores = jax.lax.dot_general(
                qg, ck.value, dn_qk).astype(jnp.float32) / np.sqrt(D)
            scores = jnp.where(vis_g[None, None], scores,
                               jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jax.lax.dot_general(
                probs.astype(qh.dtype), cv.value,
                (((3,), (2,)), ((0, 1), (0, 1))))           # [B,Hkv,rS,D]
            ctx = ctx.reshape(B, H, S, D)
            out = ctx.transpose(0, 2, 1, 3).reshape(B, S, H * D)
            return dense(E, "o_proj")(out)

        from deepspeed_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.current_mesh()
        if mesh is not None and mesh.shape.get(mesh_lib.SEQ_AXIS, 1) > 1 \
                and S % mesh.shape[mesh_lib.SEQ_AXIS] == 0:
            # the SP backends shard/rotate K/V across the seq axis at
            # full head count — repeat for them only
            if Hkv != H:
                rep = H // Hkv
                kh = jnp.repeat(kh, rep, axis=1)
                vh = jnp.repeat(vh, rep, axis=1)
            sp = mesh.shape[mesh_lib.SEQ_AXIS]
            if cfg.sp_backend == "ulysses" and H % sp == 0:
                from deepspeed_tpu.parallel.ulysses import ulysses_attention
                out = ulysses_attention(qh, kh, vh, mesh, causal=True)
            else:
                from deepspeed_tpu.parallel.ring_attention import \
                    ring_attention
                out = ring_attention(qh, kh, vh, mesh, causal=True)
        else:
            # GQA K/V go in at Hkv heads: the flash kernel's Hkv-aware
            # block maps stream the reduced cache — no full-head
            # materialization in the forward (module docstring promise)
            out = dot_product_attention(qh, kh, vh, causal=True,
                                        use_flash=cfg.use_flash)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        out = dense(E, "o_proj")(out)
        return checkpoint_name(out, "attn_proj")


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(0.02), name=name)
        gate = dense(cfg.intermediate_size, "gate_proj")(x)
        up = dense(cfg.intermediate_size, "up_proj")(x)
        h = checkpoint_name(nn.silu(gate) * up, "mlp_fc")
        out = dense(cfg.hidden_size, "down_proj")(h)
        return checkpoint_name(out, "mlp_proj")


class LlamaBlock(nn.Module):
    config: LlamaConfig
    max_out_tokens: int = 0

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        norm = lambda name: RMSNorm(  # noqa: E731
            eps=cfg.rms_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name=name)
        x = x + LlamaAttention(cfg, self.max_out_tokens, name="attn")(
            norm("input_norm")(x), positions)
        x = x + LlamaMLP(cfg, name="mlp")(norm("post_attn_norm")(x))
        return x


def _maybe_remat(cfg):
    if not cfg.remat:
        return LlamaBlock
    return nn.remat(LlamaBlock, prevent_cse=False,
                    policy=_remat_policy(cfg.remat_policy))


class _ScanBody(nn.Module):
    config: LlamaConfig
    max_out_tokens: int = 0

    @nn.compact
    def __call__(self, x, positions):
        block = _maybe_remat(self.config)
        return block(self.config, self.max_out_tokens,
                     name="blk")(x, positions), None


class LlamaForCausalLM(nn.Module):
    """Decoder-only LLaMA LM. ``labels`` triggers the fused chunked
    head+loss (models/gpt2.chunked_lm_loss works for any untied head via
    the lm_head kernel)."""
    config: LlamaConfig
    max_out_tokens: int = 0      # >0 → serving mode (KV caches)

    @nn.compact
    def __call__(self, input_ids, labels=None, deterministic=True,
                 keep_prob=1.0, position_offset=0):
        cfg = self.config
        B, S = input_ids.shape
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size),
                           cfg.param_dtype)
        x = _embed_lookup(embed, input_ids).astype(cfg.dtype)
        positions = position_offset + jnp.arange(S)

        if cfg.scan_layers:
            scanned = nn.scan(_ScanBody,
                              variable_axes={"params": 0, "cache": 0},
                              split_rngs={"params": True},
                              in_axes=(nn.broadcast,),
                              length=cfg.n_layers,
                              unroll=max(1, cfg.scan_unroll))
            x, _ = scanned(cfg, self.max_out_tokens,
                           name="layers")(x, positions)
        else:
            block = _maybe_remat(cfg)
            for i in range(cfg.n_layers):
                x = block(cfg, self.max_out_tokens,
                          name=f"layers_{i}")(x, positions)

        x = RMSNorm(eps=cfg.rms_eps, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, name="norm")(x)
        head = self.param("lm_head", nn.initializers.normal(0.02),
                          (cfg.vocab_size, cfg.hidden_size),
                          cfg.param_dtype)
        if labels is not None and cfg.loss_chunk > 0:
            return chunked_lm_loss(x, head.astype(cfg.dtype), labels,
                                   cfg.loss_chunk)
        logits = jnp.einsum("bse,ve->bsv", x, head.astype(cfg.dtype))
        if labels is not None:
            return lm_loss(logits, labels)
        return logits


# ------------------------------------------------------------- serving

import functools as _ft

_LLAMA_STEP_CACHE = {}


def _llama_compiled_steps(cfg: LlamaConfig, max_out: int):
    """(prompt_pass, decode_scan) jitted once per (config, cache length)
    — the same serving shape as models/gpt2_inference._compiled_steps."""
    key = (cfg, max_out)
    if key not in _LLAMA_STEP_CACHE:
        model = LlamaForCausalLM(cfg, max_out_tokens=max_out)

        @jax.jit
        def prompt_pass(p, ids):
            logits, vars_ = model.apply({"params": p}, ids,
                                        mutable=["cache"])
            return logits[:, -1], vars_["cache"]

        @_ft.partial(jax.jit, static_argnums=(5,), donate_argnums=(1,))
        def decode_scan(p, cache, first_tok, start, rngs, steps,
                        temperature):
            def tick(carry, r):
                cache, tok, offset = carry
                logits, vars_ = model.apply(
                    {"params": p, "cache": cache}, tok[:, None],
                    position_offset=offset, mutable=["cache"])
                logits = logits[:, -1]
                nxt = jax.lax.cond(
                    temperature > 0,
                    lambda: jax.random.categorical(
                        r, logits / jnp.maximum(temperature, 1e-6),
                        axis=-1),
                    lambda: jnp.argmax(logits, axis=-1))
                return (vars_["cache"], nxt, offset + 1), tok
            (final_cache, last, _), toks = jax.lax.scan(
                tick, (cache, first_tok, start), rngs, length=steps)
            # final cache returned so the donated input aliases an output
            # (otherwise every tick copies the caches — see
            # gpt2_inference.decode_scan)
            return jnp.concatenate(
                [toks.transpose(1, 0), last[:, None]], axis=1), final_cache

        _LLAMA_STEP_CACHE[key] = (prompt_pass, decode_scan)
    return _LLAMA_STEP_CACHE[key]


def llama_generate(cfg: LlamaConfig, params, input_ids, max_new_tokens=20,
                   temperature: float = 0.0, rng=None,
                   max_out_tokens: int = 0):
    """KV-cache generation for the LLaMA family — same contract as
    models/gpt2_inference.generate: prompt pass fills the caches, the
    whole decode loop is ONE compiled lax.scan program, temperature 0 is
    greedy. RoPE positions are absolute (position_offset), so cached
    decode matches a full re-forward exactly."""
    input_ids = jnp.asarray(input_ids)
    if max_new_tokens <= 0:
        return input_ids
    B, S = input_ids.shape
    total = S + max_new_tokens
    max_out = max_out_tokens or cfg.max_seq_len
    assert total <= max_out, (total, max_out)
    prompt_pass, decode_scan = _llama_compiled_steps(cfg, max_out)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    logits, cache = prompt_pass(params, input_ids)
    rng, sub = jax.random.split(rng)
    if temperature and temperature > 0:
        first = jax.random.categorical(sub, logits / temperature, axis=-1)
    else:
        first = jnp.argmax(logits, axis=-1)
    if max_new_tokens == 1:
        return jnp.concatenate([input_ids, first[:, None]], axis=1)
    new, _ = decode_scan(params, cache, first, jnp.asarray(S, jnp.int32),
                         jax.random.split(rng, max_new_tokens - 1),
                         max_new_tokens - 1,
                         jnp.float32(temperature or 0.0))
    return jnp.concatenate([input_ids, new], axis=1)


# ------------------------------------------------------------- TP rules

def _llama_leaf_spec(path_names, shape):
    """Megatron-style TP: q/k/v/gate/up column-parallel, o/down
    row-parallel, embeddings + head vocab-parallel, norms replicated."""
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import MODEL_AXIS
    name = path_names[-1]
    parent = path_names[-2] if len(path_names) >= 2 else ""
    ndim = len(shape)

    def spec_dim(d, axis_name):
        s = [None] * ndim
        s[d] = axis_name
        return P(*s)

    if name in ("embed_tokens", "lm_head"):
        return spec_dim(0, MODEL_AXIS)
    if parent in ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj") \
            and name == "kernel":
        return spec_dim(ndim - 1, MODEL_AXIS)
    if parent in ("o_proj", "down_proj") and name == "kernel":
        return spec_dim(ndim - 2, MODEL_AXIS)
    return P(*([None] * ndim))


def register_llama_tp_rules():
    from deepspeed_tpu.models.sharding import register_tp_rules
    register_tp_rules("LlamaForCausalLM", _llama_leaf_spec)


register_llama_tp_rules()


# ------------------------------------------------------------- presets

def llama_tiny(**over):
    kw = dict(vocab_size=512, hidden_size=128, intermediate_size=352,
              n_layers=2, n_heads=4, n_kv_heads=2, max_seq_len=128,
              dtype=jnp.float32, param_dtype=jnp.float32)
    kw.update(over)
    return LlamaConfig(**kw)


def llama_7b(**over):
    kw = dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
              n_layers=32, n_heads=32, max_seq_len=2048)
    kw.update(over)
    return LlamaConfig(**kw)


def llama3_8b(**over):
    kw = dict(vocab_size=128256, hidden_size=4096,
              intermediate_size=14336, n_layers=32, n_heads=32,
              n_kv_heads=8, max_seq_len=8192, rope_theta=500000.0)
    kw.update(over)
    return LlamaConfig(**kw)


# ------------------------------------------------------------- HF import

def from_hf_llama(hf_model, cfg: LlamaConfig, scan_layers=True):
    """transformers LlamaForCausalLM → this model's param tree. The HF
    checkpoint uses the same split-halves RoPE convention, so weights map
    1:1 (transpose only)."""
    sd = {k: np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach")
                        else v) for k, v in hf_model.state_dict().items()}

    def lin(name):
        return sd[name].T.astype(np.float32)

    layers = []
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        layers.append({
            "attn": {
                "q_proj": {"kernel": lin(p + "self_attn.q_proj.weight")},
                "k_proj": {"kernel": lin(p + "self_attn.k_proj.weight")},
                "v_proj": {"kernel": lin(p + "self_attn.v_proj.weight")},
                "o_proj": {"kernel": lin(p + "self_attn.o_proj.weight")},
            },
            "mlp": {
                "gate_proj": {"kernel": lin(p + "mlp.gate_proj.weight")},
                "up_proj": {"kernel": lin(p + "mlp.up_proj.weight")},
                "down_proj": {"kernel": lin(p + "mlp.down_proj.weight")},
            },
            "input_norm": {
                "scale": sd[p + "input_layernorm.weight"]
                .astype(np.float32)},
            "post_attn_norm": {
                "scale": sd[p + "post_attention_layernorm.weight"]
                .astype(np.float32)},
        })
    if scan_layers:
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *layers)
        tree = {"layers": {"blk": stacked}}
    else:
        tree = {f"layers_{i}": lyr for i, lyr in enumerate(layers)}
    head = sd.get("lm_head.weight",
                  sd["model.embed_tokens.weight"])  # tied fallback
    tree.update({
        "embed_tokens": jnp.asarray(
            sd["model.embed_tokens.weight"].astype(np.float32)),
        "norm": {"scale": jnp.asarray(
            sd["model.norm.weight"].astype(np.float32))},
        "lm_head": jnp.asarray(head.astype(np.float32)),
    })
    return tree
