"""GPT-2 serving path — the fused inference stack + KV-cache generation.

The reference serves GPT-2/Megatron by injecting fused inference kernels
into a live torch model (module_inject/replace_module.py:8 with
`MegatronLayerPolicy`, kernels in csrc/transformer/inference/). Here the
same role is a pure pytree conversion: training `GPT2LMHeadModel` params →
`GPT2InferenceModel` (a stack of `DeepSpeedTransformerInference` layers with
flax cache collections) + a jitted incremental `generate` loop.

Decode step cost is one [B,1,E] pass over cached K/V — bandwidth-bound,
static shapes, compiled once.
"""

import dataclasses
import functools
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.gpt2 import GPT2Config
from deepspeed_tpu.ops.transformer.inference import (
    DeepSpeedInferenceConfig,
    DeepSpeedTransformerInference,
)


def inference_config(cfg: GPT2Config, max_out_tokens: int = 0,
                     dtype=None, quantize_bits: int = 0,
                     quantize_groups: int = 1,
                     kv_cache_bits: int = 0,
                     mp_size: int = 1) -> DeepSpeedInferenceConfig:
    return DeepSpeedInferenceConfig(
        mp_size=mp_size,
        hidden_size=cfg.n_embd,
        heads=cfg.n_head,
        layer_norm_eps=cfg.layer_norm_epsilon,
        pre_layer_norm=True,
        triangular_masking=True,
        max_out_tokens=max_out_tokens or cfg.n_positions,
        gelu_approximate=True,   # GPT-2 trains with tanh-approx GELU
        moe_experts=cfg.moe_experts,
        moe_k=cfg.moe_k,
        moe_capacity_factor=cfg.moe_capacity_factor,
        quantize_bits=quantize_bits,
        quantize_groups=quantize_groups,
        kv_cache_bits=kv_cache_bits,
        dtype=dtype or cfg.dtype,
        param_dtype=cfg.param_dtype,
    )


class _ScanInferenceLayer(nn.Module):
    config: DeepSpeedInferenceConfig

    @nn.compact
    def __call__(self, x, attention_mask):
        layer = DeepSpeedTransformerInference(self.config, name="blk")
        return layer(x, attention_mask), None


class GPT2InferenceModel(nn.Module):
    """GPT-2 LM built on the fused inference layer. Param layout mirrors the
    training model's embeddings (`wte`/`wpe`/`ln_f`) with injected fused
    blocks under `h/blk` (scan) — produced by `convert_gpt2_params`."""
    config: GPT2Config
    max_out_tokens: int = 0
    quantize_bits: int = 0      # int8-storage serving (4x weight memory)
    quantize_groups: int = 1
    kv_cache_bits: int = 0      # int8 KV cache (2x cache memory vs bf16)
    mp_size: int = 1            # model-axis TP shards (reference mp_size)

    @nn.compact
    def __call__(self, input_ids, position_offset=0):
        cfg = self.config
        icfg = inference_config(cfg, self.max_out_tokens,
                                quantize_bits=self.quantize_bits,
                                quantize_groups=self.quantize_groups,
                                kv_cache_bits=self.kv_cache_bits,
                                mp_size=self.mp_size)
        B, S = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), cfg.param_dtype)
        pos = position_offset + jnp.arange(S)
        x = wte[input_ids].astype(cfg.dtype) \
            + wpe[pos][None].astype(cfg.dtype)

        # unroll the layer scan (GPT2Config.scan_unroll): decode ticks are
        # ~15 small ops per layer, so per-iteration fixed costs are a real
        # fraction of the token; unrolling also lets XLA fuse elementwise
        # chains across layers. Measured serving-config dependent (r4
        # ablation) — the serving entry points pick their measured best.
        scanned = nn.scan(_ScanInferenceLayer,
                          variable_axes={"params": 0, "cache": 0},
                          split_rngs={"params": True},
                          in_axes=(nn.broadcast,),
                          length=cfg.n_layer,
                          unroll=max(1, cfg.scan_unroll))
        x, _ = scanned(icfg, name="h")(x, None)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if cfg.tie_word_embeddings:
            return jnp.einsum("bse,ve->bsv", x, wte.astype(cfg.dtype))
        return nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head")(x)


def _convert_block(blk):
    """Training Block subtree → fused inference layer subtree (the weight
    copy of replace_module.py:24-79; orientations are identical since both
    sides are flax Dense kernels [in, out]). MoE blocks carry their
    gate+expert bank through verbatim (the inference layer instantiates
    the same MoE module under the same name)."""
    out = {
        "attn_nw": dict(blk["ln_1"]),
        "attn_qkvw": dict(blk["attn"]["c_attn"]),
        "attn_ow": dict(blk["attn"]["c_proj"]),
        "norm_w": dict(blk["ln_2"]),
    }
    if "moe" in blk:
        out["moe"] = dict(blk["moe"])
    else:
        out["inter_w"] = dict(blk["mlp"]["c_fc"])
        out["output_w"] = dict(blk["mlp"]["c_proj"])
    return out


def convert_gpt2_params(params, cfg: GPT2Config):
    """Training `GPT2LMHeadModel` params → `GPT2InferenceModel` params.

    Handles both layouts: scan-stacked (`h/blk/...` leaves with a leading
    [L] axis — converted wholesale, the stacking carries over) and unrolled
    (`h_0`..`h_{L-1}` — re-stacked onto a leading layer axis)."""
    out = {"wte": params["wte"], "wpe": params["wpe"],
           "ln_f": dict(params["ln_f"])}
    if not cfg.tie_word_embeddings:
        out["lm_head"] = dict(params["lm_head"])
    if "h" in params:
        out["h"] = {"blk": _convert_block(params["h"]["blk"])}
    else:
        blocks = [_convert_block(params[f"h_{i}"])
                  for i in range(cfg.n_layer)]
        out["h"] = {"blk": jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *blocks)}
    return out


_STEP_CACHE = {}


def _compiled_steps(cfg: GPT2Config, max_out: int, quantize_bits: int = 0,
                    quantize_groups: int = 1, kv_cache_bits: int = 0,
                    mp_size: int = 1):
    """(prompt_pass, decode_step, decode_scan) jitted once per (config,
    cache length) — repeated generate() calls hit jit's cache instead of
    retracing the whole model per request. decode_scan additionally
    recompiles per distinct step COUNT (its scan length is static);
    callers generating many different lengths should bucket them or use
    the per-token decode_step path (generate(..., scan_decode=False))."""
    key = (cfg, max_out, quantize_bits, quantize_groups, kv_cache_bits,
           mp_size)
    if key not in _STEP_CACHE:
        model = GPT2InferenceModel(cfg, max_out_tokens=max_out,
                                   quantize_bits=quantize_bits,
                                   quantize_groups=quantize_groups,
                                   kv_cache_bits=kv_cache_bits,
                                   mp_size=mp_size)

        @jax.jit
        def prompt_pass(p, ids):
            logits, vars_ = model.apply({"params": p}, ids,
                                        mutable=["cache"])
            return logits[:, -1], vars_["cache"]

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_step(p, cache, tok, offset):
            # donated cache: the update aliases in place instead of
            # copying the (multi-GB at batch) KV buffers every token
            logits, vars_ = model.apply(
                {"params": p, "cache": cache}, tok[:, None],
                position_offset=offset, mutable=["cache"])
            return logits[:, -1], vars_["cache"]

        @functools.partial(jax.jit, static_argnums=(5,),
                           donate_argnums=(1,))
        def decode_scan(p, cache, first_tok, start, rngs, steps,
                        temperature):
            """The whole decode loop as ONE compiled program (one host
            dispatch for `steps` tokens — on dispatch-latency-bound
            backends the python per-token loop costs more than the math).
            `temperature` is a traced operand so per-request sampling
            temperatures don't recompile."""
            def tick(carry, r):
                cache, tok, offset = carry
                logits, vars_ = model.apply(
                    {"params": p, "cache": cache}, tok[:, None],
                    position_offset=offset, mutable=["cache"])
                logits = logits[:, -1]
                # cond, not where: greedy decode must not pay the Gumbel
                # sampling over [B, V] every tick (the tick body is
                # collective-free, so diverging branches are safe here)
                nxt = jax.lax.cond(
                    temperature > 0,
                    lambda: jax.random.categorical(
                        r, logits / jnp.maximum(temperature, 1e-6), axis=-1),
                    lambda: jnp.argmax(logits, axis=-1))
                return (vars_["cache"], nxt, offset + 1), tok
            (final_cache, last, _), toks = jax.lax.scan(
                tick, (cache, first_tok, start), rngs, length=steps)
            # toks are the INPUT tokens of each tick: [steps, B] starting
            # with first_tok; append the final pick for steps+1 outputs.
            # The final cache is RETURNED (callers discard it) so the
            # donated input cache has an output to alias: without it XLA
            # cannot run the per-tick cache updates in place and copies
            # the full multi-MB caches through slice/update fusions every
            # layer every tick (~0.9 ms/token at GPT-2-large/2k — the
            # device trace's dynamic-slice/update fusions).
            return jnp.concatenate(
                [toks.transpose(1, 0), last[:, None]], axis=1), final_cache

        _STEP_CACHE[key] = (prompt_pass, decode_step, decode_scan)
    return _STEP_CACHE[key]


def quantize_gpt2_inference_params(iparams, groups: int = 1):
    """Injected inference params → int8-storage params (serve with
    `generate(..., quantize_bits=8)`): ~4x less HBM for the layer weights."""
    from deepspeed_tpu.ops.transformer.inference import \
        quantize_inference_params
    return quantize_inference_params(iparams, bits=8, groups=groups)




def gpt2_inference_tp_specs(iparams):
    """PartitionSpec tree for mp_size-sharded GPT-2 serving over the mesh
    'model' axis (the reference's module_inject mp_size sharding,
    replace_module.py:16-17), extended to the scan-stacked [L, ...] leaf
    layout this model uses: qkv + FFN-in column-parallel, output
    projections row-parallel, embeddings/norms/scales replicated. Works
    for both bf16 (`kernel`) and int8-storage (`kernel_q`) trees."""
    from deepspeed_tpu.parallel.mesh import MODEL_AXIS
    from jax.sharding import PartitionSpec as P

    def leaf_spec(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        nd = getattr(leaf, "ndim", 0)
        col = any(n in ("attn_qkvw", "inter_w") for n in names)
        row = any(n in ("attn_ow", "output_w") for n in names)
        last = names[-1] if names else ""
        if last in ("kernel", "kernel_q") and nd >= 2:
            if col:
                return P(*([None] * (nd - 1) + [MODEL_AXIS]))
            if row:
                return P(*([None] * (nd - 2) + [MODEL_AXIS, None]))
        if last == "bias" and col and nd >= 1:
            return P(*([None] * (nd - 1) + [MODEL_AXIS]))
        return P()
    return jax.tree_util.tree_map_with_path(leaf_spec, iparams)




def shard_inference_params(iparams, mesh):
    """device_put the (converted) inference params onto the mesh with the
    mp_size TP layout. Serving loops should call this ONCE and pass the
    sharded tree to every generate(): generate() skips the transfer when
    the leaves already carry the target shardings, but host/unsharded
    trees would otherwise be re-transferred per request."""
    from jax.sharding import NamedSharding
    specs = gpt2_inference_tp_specs(iparams)
    targets = jax.tree_util.tree_map(
        lambda sp: NamedSharding(mesh, sp), specs)
    # device_put is a no-op per leaf whose sharding already matches, so
    # repeated calls with a pre-sharded tree transfer nothing
    return jax.device_put(iparams, targets)




def _supports_fast_decode(cfg: GPT2Config, B, quantize_bits,
                          quantize_groups, kv_cache_bits, mp_size):
    """Gate for the fused manual serving loop. Any combination of
    {bf16, int8} weights x {bf16, int8} KV cache is fused — the decode
    kernels are dtype-agnostic on the weight path (the reference's
    inference kernels are fp16-FIRST; quantization is an option, not a
    prerequisite: csrc/transformer/inference/csrc/pt_binding.cpp)."""
    return (quantize_bits in (0, 8) and kv_cache_bits in (0, 8)
            and (quantize_bits == 0 or quantize_groups == 1)
            and mp_size == 1 and B <= 64
            and cfg.n_embd % 128 == 0 and (4 * cfg.n_embd) % 128 == 0
            and cfg.scan_layers and cfg.moe_experts == 0
            and cfg.tie_word_embeddings)


def _fast_decode_scan_fn(cfg: GPT2Config, max_out: int,
                         weights_q8: bool = True, cache_q8: bool = True):
    """Manual serving loop over STACKED weights/caches — the flax
    nn.scan path slices every stacked array per layer per tick (~60% of
    the decode token in slice/unslice copies, device trace r4c); here
    the layer loop carries the whole caches (one in-place row update
    each) and the Pallas kernels index the weight/cache stacks directly
    via scalar-prefetched block maps (ops/pallas/decode.py *_stacked).

    ``weights_q8``/``cache_q8`` select int8 vs bf16 storage per side:
    the weight kernels are dtype-agnostic (bf16 stacks run with
    scale=1), the attention kernel has int8- and fp-cache variants, and
    bf16 caches skip the kv-quant kernel entirely (3 Pallas calls per
    layer instead of 4)."""
    key = ("fast", cfg, max_out, weights_q8, cache_q8)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]
    from deepspeed_tpu.ops.pallas.decode import (
        ln_qkv_int8_stacked, kv_quant_int8, decode_attention_int8_stacked,
        decode_attention_fp_stacked, out_ffn_int8_stacked)
    E, H = cfg.n_embd, cfg.n_head
    D = E // H
    Lyr = cfg.n_layer
    eps = cfg.layer_norm_epsilon

    def _ln_f(x, w, b):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + eps)
        return (y * w.astype(jnp.float32)
                + b.astype(jnp.float32)).astype(x.dtype)

    wkey = "kernel_q" if weights_q8 else "kernel"

    def _wscale(proj):
        if weights_q8:
            return proj["kernel_scale"].reshape(Lyr)
        return jnp.ones((Lyr,), jnp.float32)

    @functools.partial(jax.jit, static_argnums=(4,),
                       donate_argnums=(2,))
    def fast_scan(p, blk, caches, first_tok, steps, start, rngs,
                  temperature):
        wte = jnp.asarray(p["wte"]).astype(cfg.dtype)
        wpe = jnp.asarray(p["wpe"]).astype(cfg.dtype)
        lnf_w, lnf_b = p["ln_f"]["scale"], p["ln_f"]["bias"]
        Wq = blk["attn_qkvw"][wkey]
        Wp = blk["attn_ow"][wkey]
        W1 = blk["inter_w"][wkey]
        W2 = blk["output_w"][wkey]
        # every per-layer parameter stays STACKED — the kernels fetch
        # their own layer's LN/bias tiles via layer-indexed block maps
        # and read the per-tensor scales from SMEM prefetch vectors.
        # (13 per-layer xs here cost ~15-20 us of slice/copy overhead
        # EACH per layer on this target — r5 b32 device trace.)
        # [Lyr, 1, cols] so the kernels' per-layer blocks are (1,1,cols)
        # — reshaped ONCE here, not per layer call (layout copy)
        r3 = lambda a: a.reshape(Lyr, 1, a.shape[-1])
        ln1_w, ln1_b = r3(blk["attn_nw"]["scale"]), r3(blk["attn_nw"]["bias"])
        ln2_w, ln2_b = r3(blk["norm_w"]["scale"]), r3(blk["norm_w"]["bias"])
        bq = r3(blk["attn_qkvw"]["bias"])
        bp = r3(blk["attn_ow"]["bias"])
        b1 = r3(blk["inter_w"]["bias"])
        b2 = r3(blk["output_w"]["bias"])
        sq = _wscale(blk["attn_qkvw"])
        sp_ = _wscale(blk["attn_ow"])
        s1 = _wscale(blk["inter_w"])
        s2 = _wscale(blk["output_w"])
        B = first_tok.shape[0]
        L_cache = caches[0].shape[3]
        if cache_q8:
            # scale arrays live lane-major [Lyr, B, H, 1, L] for the
            # attention kernel's block maps; reshaping per layer call
            # materializes a full-stack copy each time (tiled layouts
            # differ), so do it ONCE here
            kc, ks, vc, vs = caches
            caches = (kc, ks.reshape(Lyr, B, H, 1, L_cache),
                      vc, vs.reshape(Lyr, B, H, 1, L_cache))

        def tick(carry, r):
            caches, tok, offset = carry
            x = wte[tok] + wpe[offset][None]         # [B, E]
            # overflow: clamped row writes would silently serve stale
            # context — poison, same contract as the flax path
            x = jnp.where(offset >= L_cache,
                          jnp.float32(jnp.nan).astype(x.dtype), x)

            def layer(car, l):
                x, caches = car
                qkv = ln_qkv_int8_stacked(x, ln1_w, ln1_b, Wq, sq, bq, l,
                                          eps=eps)
                q = qkv[:, :E]
                k3 = qkv[:, E:2 * E].reshape(B, H, D)
                v3 = qkv[:, 2 * E:].reshape(B, H, D)
                dus = jax.lax.dynamic_update_slice
                qh = q.reshape(B, 1, H, D).transpose(0, 2, 1, 3)
                if cache_q8:
                    kc, ks, vc, vs = caches
                    kq8, ksc, vq8, vsc = kv_quant_int8(k3, v3)
                    kc = dus(kc, kq8[None, :, :, None, :],
                             (l, 0, 0, offset, 0))
                    vc = dus(vc, vq8[None, :, :, None, :],
                             (l, 0, 0, offset, 0))
                    ks = dus(ks, ksc.reshape(1, B, H, 1, 1),
                             (l, 0, 0, 0, offset))
                    vs = dus(vs, vsc.reshape(1, B, H, 1, 1),
                             (l, 0, 0, 0, offset))
                    ctx = decode_attention_int8_stacked(
                        qh, kc, ks, vc, vs, offset, l,
                        scale=1.0 / np.sqrt(D))
                    caches = (kc, ks, vc, vs)
                else:
                    kc, vc = caches
                    kc = dus(kc, k3[None, :, :, None, :].astype(kc.dtype),
                             (l, 0, 0, offset, 0))
                    vc = dus(vc, v3[None, :, :, None, :].astype(vc.dtype),
                             (l, 0, 0, offset, 0))
                    ctx = decode_attention_fp_stacked(
                        qh, kc, vc, offset, l, scale=1.0 / np.sqrt(D))
                    caches = (kc, vc)
                ctx2 = ctx.transpose(0, 2, 1, 3).reshape(B, E)
                x = out_ffn_int8_stacked(
                    ctx2, x, Wp, sp_, bp, ln2_w, ln2_b, W1, s1, b1, W2,
                    s2, b2, l,
                    act="gelu_tanh", eps=eps)
                return (x, caches), None

            (x, caches), _ = jax.lax.scan(
                layer, (x, caches), jnp.arange(Lyr, dtype=jnp.int32))
            logits = jnp.einsum("be,ve->bv", _ln_f(x, lnf_w, lnf_b), wte)
            nxt = jax.lax.cond(
                temperature > 0,
                lambda: jax.random.categorical(
                    r, logits.astype(jnp.float32)
                    / jnp.maximum(temperature, 1e-6), axis=-1),
                lambda: jnp.argmax(logits, axis=-1))
            return (caches, nxt, offset + 1), tok

        (caches, last, _), toks = jax.lax.scan(
            tick, (caches, first_tok, start), rngs, length=steps)
        return (jnp.concatenate([toks.transpose(1, 0), last[:, None]],
                                axis=1), caches)

    _STEP_CACHE[key] = fast_scan
    return fast_scan


def generate(cfg: GPT2Config, params, input_ids, max_new_tokens=20,
             temperature: float = 0.0, rng=None, max_out_tokens: int = 0,
             quantize_bits: int = 0, quantize_groups: int = 1,
             kv_cache_bits: int = 0, scan_decode: bool = True,
             mesh=None):
    """KV-cache generation. ``temperature == 0`` → greedy. Returns
    [B, S + max_new_tokens] token ids.

    Prompt processing fills the cache in one pass. With ``scan_decode``
    (default) the whole decode loop is one compiled ``lax.scan`` program —
    a single host dispatch for all new tokens, which is what decode
    latency is actually made of on dispatch-bound backends (measured 4x+
    on a tunneled v5e; the per-token math at batch 1 is ~2 ms of HBM
    reads). ``scan_decode=False`` keeps the one-jitted-step-per-token
    loop (compiled once per config; useful for streaming callers).
    ``quantize_bits=8`` serves int8-stored weights (params must come from
    `quantize_gpt2_inference_params`)."""
    input_ids = jnp.asarray(input_ids)
    B, S = input_ids.shape
    total = S + max_new_tokens
    # every emitted position needs a real learned position embedding —
    # beyond n_positions the wpe gather would clamp and silently corrupt
    assert total <= cfg.n_positions, (
        f"prompt {S} + max_new_tokens {max_new_tokens} exceeds "
        f"n_positions {cfg.n_positions}")
    max_out = max_out_tokens or cfg.n_positions
    assert total <= max_out, (total, max_out)
    # mp_size serving (reference module_inject mp_size): layer weights
    # shard over the mesh model axis; GSPMD propagates the head sharding
    # onto the KV caches and inserts the row-parallel psums
    mp_size = 1
    if mesh is not None:
        from deepspeed_tpu.parallel.mesh import MODEL_AXIS
        mp_size = int(mesh.shape.get(MODEL_AXIS, 1))
        if mp_size > 1:
            assert cfg.n_head % mp_size == 0, (
                f"n_head {cfg.n_head} must divide over the model axis "
                f"({mp_size} shards)")
    prompt_pass, decode_step, decode_scan = _compiled_steps(
        cfg, max_out, quantize_bits, quantize_groups, kv_cache_bits,
        mp_size)
    converted = "h" in params and "blk" in params.get("h", {}) and \
        any(k in params["h"]["blk"] for k in ("attn_qkvw",))
    iparams = params if converted else convert_gpt2_params(params, cfg)
    if mp_size > 1:
        iparams = shard_inference_params(iparams, mesh)

    def pick(logits, r):
        if temperature and temperature > 0:
            return jax.random.categorical(r, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    rng = rng if rng is not None else jax.random.PRNGKey(0)
    logits, cache = prompt_pass(iparams, input_ids)

    if scan_decode and max_new_tokens > 1:
        rng, sub = jax.random.split(rng)
        first = pick(logits, sub)
        if _supports_fast_decode(cfg, B, quantize_bits, quantize_groups,
                                 kv_cache_bits, mp_size):
            fast = _fast_decode_scan_fn(cfg, max_out,
                                        weights_q8=quantize_bits == 8,
                                        cache_q8=kv_cache_bits == 8)
            blk = iparams["h"]["blk"]
            cblk = cache["h"]["blk"]
            if kv_cache_bits == 8:
                caches = (cblk["cached_key_q8"], cblk["key_scale"],
                          cblk["cached_value_q8"], cblk["value_scale"])
            else:
                caches = (cblk["cached_key"], cblk["cached_value"])
            new, _ = fast(
                {"wte": iparams["wte"], "wpe": iparams["wpe"],
                 "ln_f": iparams["ln_f"]}, blk, caches,
                first, max_new_tokens - 1, jnp.asarray(S, jnp.int32),
                jax.random.split(rng, max_new_tokens - 1),
                jnp.float32(temperature or 0.0))
            return jnp.concatenate([input_ids, new], axis=1)
        new, _ = decode_scan(iparams, cache, first,
                             jnp.asarray(S, jnp.int32),
                             jax.random.split(rng, max_new_tokens - 1),
                             max_new_tokens - 1,
                             jnp.float32(temperature or 0.0))
        return jnp.concatenate([input_ids, new], axis=1)

    toks = [input_ids]
    for i in range(max_new_tokens):
        rng, sub = jax.random.split(rng)
        nxt = pick(logits, sub)
        toks.append(nxt[:, None])
        if i + 1 < max_new_tokens:
            # offset as a device scalar so the step compiles exactly once
            logits, cache = decode_step(iparams, cache, nxt,
                                        jnp.asarray(S + i, jnp.int32))
    return jnp.concatenate(toks, axis=1)
