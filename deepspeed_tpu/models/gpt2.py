"""GPT-2 family — the flagship model, TPU-first.

The reference trains GPT-2 through the external Megatron-LM client
(tests/model/Megatron_GPT2, SURVEY §4); here the model is in-tree flax with:

- bf16 activations, fp32 params (master-weight policy handled by the engine)
- optional `scan` over layers (one compiled block body — fast compiles for
  48-layer 1.5B configs, and the natural layout for pipeline stages)
- optional remat (activation checkpointing, reference
  activation_checkpointing/checkpointing.py analog via jax.checkpoint)
- flash attention via Pallas on TPU
- logical parameter axes for GSPMD: TP over heads/mlp/vocab, ZeRO-3 over the
  remaining large axis (see deepspeed_tpu/runtime/zero/partition.py)
- progressive layer drop keep-prob input (reference
  runtime/progressive_layer_drop.py:5 passes theta into fwd kwargs)
"""

import dataclasses
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.ops.attention import dot_product_attention


import functools as _functools


def _gspmd_mesh():
    """Mesh for the model's GSPMD layout pins (wpe slice, wte scatter),
    or None when pins must not apply. The mesh comes from the ENGINE's
    trace-scoped mesh_lib.layout_pins(...) — never the ambient registry:
    set_current_mesh outlives its engine, and a later trace (another
    engine, the pipeline executor, a bare-model test) constraining to a
    stale foreign-device mesh crashes GSPMD (the r4 full-suite abort).
    Pins are also off inside explicit-comm (shard_map) programs, where
    data is already device-local and a NamedSharding over the global
    (Auto-axis) mesh poisons downstream avals — the engine flags those
    via no_layout_pins() because trace-context sniffing is unreliable
    (custom_vjp backwards re-trace under whatever mesh context is live
    at transpose time); the Manual axis check additionally catches
    direct shard_map use of the model."""
    from deepspeed_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.pinned_mesh()
    if mesh is None:
        return None
    if mesh_lib.in_manual_region():
        return None
    return mesh


@_functools.lru_cache(maxsize=None)
def _embed_lookup_fn(shape, dtype_name):
    """Token-embedding gather whose backward pins the scatter-add to the
    vocab-parallel (TP-only) layout. Without the pin, shardy propagates the
    ZeRO opt-state sharding (data axis on the vocab dim) onto the scatter
    output while the updates stay batch-sharded — GSPMD then cannot
    partition the scatter and falls back to involuntary full
    rematerialization (a whole-cotangent broadcast every step). Pinned to
    the TP spec, the scatter partitions as masked local updates + a data
    psum, and the cheap TP→opt reshard happens on the finished gradient."""
    @jax.custom_vjp
    def f(wte, ids):
        return wte[ids]

    def fwd(wte, ids):
        return wte[ids], ids

    def bwd(ids, g):
        d = jnp.zeros(shape, g.dtype).at[ids].add(g)
        from deepspeed_tpu.parallel import mesh as mesh_lib
        from jax.sharding import NamedSharding, PartitionSpec
        # the engine's layout_pins context is a PYTHON-call-scoped flag,
        # so it is still live however/whenever jax re-traces this
        # backward (custom_vjp backwards re-trace under arbitrary mesh
        # contexts at transpose time — context sniffing here misfires)
        mesh = mesh_lib.pinned_mesh()
        if mesh is not None:
            spec = PartitionSpec(mesh_lib.MODEL_AXIS, None) \
                if mesh.shape.get(mesh_lib.MODEL_AXIS, 1) > 1 \
                else PartitionSpec()
            d = jax.lax.with_sharding_constraint(
                d, NamedSharding(mesh, spec))
        return d.astype(dtype_name), None

    f.defvjp(fwd, bwd)
    return f


def _embed_lookup(wte, ids):
    return _embed_lookup_fn(tuple(wte.shape),
                            jnp.dtype(wte.dtype).name)(wte, ids)


def _expert_mesh_batch_pin(t):
    """Batch-layout constraint applied only under a live EXPERT mesh
    axis. Tiling the batch dim over the ('data','expert') axis pair
    yields a device order XLA's partitioner cannot convert to/from the
    model-axis tilings it picks inside the layer scan — the conversion
    degenerates to involuntary full rematerialization (a whole-tensor
    broadcast per step; the dryrun detector's dp×ep×tp tripper, clean
    on dp×sp×tp and dp×tp meshes). Anchoring the tensor to the batch
    layout keeps every reshard on a convertible path. No-op outside an
    engine-pinned GSPMD trace or when no expert axis is live."""
    from deepspeed_tpu.parallel import mesh as mesh_lib
    mesh = _gspmd_mesh()
    if mesh is None or mesh.shape.get(mesh_lib.EXPERT_AXIS, 1) <= 1:
        return t
    return jax.lax.with_sharding_constraint(
        t, mesh_lib.batch_sharding(mesh))


@_functools.lru_cache(maxsize=None)
def _carry_pin_fn():
    """Identity whose primal AND cotangent pin to the batch layout on
    expert meshes (the layer-scan carry spec enrichment): the backward
    scan otherwise carries the residual-stream cotangent model-major
    and remats flipping it back to batch-major."""
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return _expert_mesh_batch_pin(x), None

    def bwd(_, g):
        # the engine's layout_pins context is Python-call-scoped, so it
        # is live however/whenever jax re-traces this backward
        return (_expert_mesh_batch_pin(g),)

    f.defvjp(fwd, bwd)
    return f


def _carry_pin(x):
    # trace-time gate: the engine's layout_pins context is live for the
    # whole trace, so whether an expert axis exists is a stable Python
    # fact — skip inserting the custom_vjp entirely on non-expert
    # meshes (the overwhelmingly common case; keeps those traces and
    # compiles free of dead identity nodes)
    from deepspeed_tpu.parallel import mesh as mesh_lib
    mesh = _gspmd_mesh()
    if mesh is None or mesh.shape.get(mesh_lib.EXPERT_AXIS, 1) <= 1:
        return x
    return _carry_pin_fn()(x)


class CollectiveDense(nn.Dense):
    """``nn.Dense`` twin whose kernel GEMM can fuse with the ZeRO-3
    gather ring (ISSUE 8). Outside a fused-gather trace this IS
    ``nn.Dense`` — same param tree, same promote/dot/bias numerics, so
    every GSPMD/serving/inference path is untouched. Inside the
    prefetch pipeline's ``fused_matmul`` body traces
    (ops/pallas/fused_collective.gather_scope) the pipeline leaves a
    layer's dominant projection kernels in the param tree as their
    RESTING SHARDS; a shard-shaped kernel value routes the GEMM
    through ``collective_matmul`` — the all-gather decomposed into
    ring chunks interleaved with the GEMM tiles that consume them,
    backward dW through matmul+reduce-scatter — so the materialized
    full weight never exists. Detection is by shape: flax's
    declared-param check would reject a shard, so the fused path reads
    the raw variable (``scope.get_variable``) and declares only the
    bias; full-shaped kernels (leaves the pipeline gathered normally)
    fall through to the stock Dense path even under an active scope."""

    @nn.compact
    def __call__(self, inputs):
        from deepspeed_tpu.ops.pallas import fused_collective as fc
        cfg = fc.gather_ctx()
        in_dim = jnp.shape(inputs)[-1]
        if cfg is not None and self.scope.has_variable("params", "kernel"):
            raw = self.scope.get_variable("params", "kernel")
            shard_dim = fc.infer_shard_dim(jnp.shape(raw), in_dim,
                                           self.features, cfg.axis_size)
            if shard_dim is not None:
                from flax.linen.dtypes import promote_dtype
                bias = self.param("bias", self.bias_init, (self.features,),
                                  self.param_dtype) if self.use_bias \
                    else None
                x, shard, bias = promote_dtype(inputs, raw, bias,
                                               dtype=self.dtype)
                y = fc.collective_matmul(
                    x, shard, shard_dim=shard_dim,
                    axis_name=cfg.axis_name, axis_size=cfg.axis_size,
                    cfg=cfg, precision=self.precision,
                    site="/".join(self.scope.path))
                if bias is not None:
                    y = y + jnp.reshape(bias,
                                        (1,) * (y.ndim - 1) + (-1,))
                return y
        # fallthrough: the stock nn.Dense body verbatim (flax 0.10) —
        # the @compact-wrapped Dense.__call__ cannot be super()-called
        # from another compact method, and identical numerics (same
        # promote, same dot_general, same bias broadcast) is the
        # contract tests/test_prefetch.py pins against model.apply
        from flax.linen.dtypes import promote_dtype
        kernel = self.param("kernel", self.kernel_init,
                            (in_dim, self.features), self.param_dtype)
        bias = self.param("bias", self.bias_init, (self.features,),
                          self.param_dtype) if self.use_bias else None
        inputs, kernel, bias = promote_dtype(inputs, kernel, bias,
                                             dtype=self.dtype)
        y = jax.lax.dot_general(
            inputs, kernel, (((inputs.ndim - 1,), (0,)), ((), ())),
            precision=self.precision)
        if bias is not None:
            y += jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
        return y


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16          # activation/compute dtype (MXU-friendly)
    param_dtype: Any = jnp.float32     # master params
    remat: bool = False
    remat_policy: Optional[str] = None  # None=full remat | "dots" | "offload"
    sp_backend: str = "ring"            # "ring" | "ulysses" (seq-axis attn)
    moe_experts: int = 0                # >0 → MoE FFN (expert parallel)
    moe_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 0.01         # load-balance loss weight
    scan_layers: bool = True
    # unroll factor for the layer scan: >1 lets XLA fuse/schedule across
    # adjacent layers and amortizes per-iteration fixed costs at the price
    # of code size / compile time. Must divide n_layer.
    scan_unroll: int = 1
    use_flash: Optional[bool] = None   # None = auto (TPU yes)
    tie_word_embeddings: bool = True
    # fused head+loss: when __call__ gets `labels`, compute the LM cross
    # entropy in chunks of this many tokens instead of materializing the
    # [B, S, V] logits (f32 lse temporaries are >1 GB at V=50k) — the
    # memory knob that lets dots-policy remat fit a 16 GB chip. 0 = off.
    loss_chunk: int = 0

    @property
    def head_dim(self):
        return self.n_embd // self.n_head

    def num_params(self):
        V, P, E, L = self.vocab_size, self.n_positions, self.n_embd, self.n_layer
        per_layer = 12 * E * E + 13 * E
        return V * E + P * E + L * per_layer + 2 * E


class SelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        B, S, E = x.shape
        qkv = CollectiveDense(3 * E, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype,
                              kernel_init=nn.initializers.normal(0.02),
                              name="c_attn")(x)
        qkv = checkpoint_name(qkv, "qkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, S, cfg.n_head, cfg.head_dim).transpose(0, 2, 1, 3)

        # sequence parallelism: when the active mesh has a seq axis, run
        # ring or Ulysses attention over it instead of letting GSPMD gather
        # full K/V
        from deepspeed_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.current_mesh()
        if mesh is not None and mesh.shape.get(mesh_lib.SEQ_AXIS, 1) > 1 \
                and S % mesh.shape[mesh_lib.SEQ_AXIS] == 0:
            sp = mesh.shape[mesh_lib.SEQ_AXIS]
            if cfg.sp_backend == "ulysses" and cfg.n_head % sp != 0:
                # Ulysses scatters heads over the seq axis, so it also needs
                # n_head % sp == 0; fall back to ring attention (which has no
                # head constraint) rather than tripping a trace-time assert
                # inside the a2a — but say so, the user asked for ulysses.
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    f"sp_backend='ulysses' needs n_head ({cfg.n_head}) "
                    f"divisible by the seq axis ({sp}); falling back to "
                    f"ring attention")
            if cfg.sp_backend == "ulysses" and cfg.n_head % sp == 0:
                from deepspeed_tpu.parallel.ulysses import ulysses_attention
                out = ulysses_attention(heads(q), heads(k), heads(v), mesh,
                                        causal=True)
            else:
                from deepspeed_tpu.parallel.ring_attention import ring_attention
                out = ring_attention(heads(q), heads(k), heads(v), mesh,
                                     causal=True)
        else:
            out = dot_product_attention(heads(q), heads(k), heads(v),
                                        causal=True, use_flash=cfg.use_flash)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, E)
        out = CollectiveDense(E, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype,
                              kernel_init=nn.initializers.normal(
                                  0.02 / np.sqrt(2 * cfg.n_layer)),
                              name="c_proj")(out)
        out = checkpoint_name(out, "attn_proj")
        if cfg.dropout > 0:
            out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        h = CollectiveDense(4 * cfg.n_embd, dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            kernel_init=nn.initializers.normal(0.02),
                            name="c_fc")(x)
        h = checkpoint_name(h, "mlp_fc")
        h = nn.gelu(h, approximate=True)
        h = CollectiveDense(cfg.n_embd, dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            kernel_init=nn.initializers.normal(
                                0.02 / np.sqrt(2 * cfg.n_layer)),
                            name="c_proj")(h)
        h = checkpoint_name(h, "mlp_proj")
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    """Pre-LN transformer block (GPT-2 style). ``keep_prob`` implements
    progressive layer drop: output = x + keep * sublayer(x) with the engine
    feeding the PLD theta schedule."""
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True, keep_prob=1.0):
        cfg = self.config
        # keep dtype stable under a traced keep_prob (PLD schedule is fp32)
        keep = jnp.asarray(keep_prob, x.dtype)
        ln1 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                           param_dtype=cfg.param_dtype, name="ln_1")(x)
        x = x + keep * SelfAttention(cfg, name="attn")(ln1, deterministic)
        x = _carry_pin(x)
        ln2 = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                           param_dtype=cfg.param_dtype, name="ln_2")(x)
        if cfg.moe_experts:
            from deepspeed_tpu.moe import MoE
            ffn_out = MoE(num_experts=cfg.moe_experts,
                          d_ff=4 * cfg.n_embd, k=cfg.moe_k,
                          capacity_factor=cfg.moe_capacity_factor,
                          dropout=cfg.dropout,
                          out_init_std=0.02 / np.sqrt(2 * cfg.n_layer),
                          dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                          name="moe")(ln2, deterministic)
        else:
            ffn_out = MLP(cfg, name="mlp")(ln2, deterministic)
        x = x + keep * ffn_out
        return _carry_pin(x)


def _remat_policy(name):
    """Named remat policies (the memory/compute knobs of the reference's
    activation_checkpointing config, SURVEY §5.7): full remat (None), keep
    matmul outputs on-chip ("dots"), or offload saved residuals to host
    memory ("offload" — the cpu_checkpointing analog)."""
    if name is None:
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "dots_lite":
        # save qkv (3E) + both residual-branch projections (2E) per layer
        # but NOT the 4E mlp fc output — 5E/9E of the "dots" footprint for
        # one extra fc matmul (1/3 of forward flops) recomputed in backward.
        return jax.checkpoint_policies.save_only_these_names(
            "qkv", "attn_proj", "mlp_proj")
    if name == "dots_flash":
        # dots_lite + the flash-attention kernel's own residuals (output +
        # logsumexp): backward runs the flash bwd kernels directly instead
        # of re-executing the forward kernel first. +1E per layer over
        # dots_lite; the best-measured fit for 16 GB at GPT-2-large/bs8
        # once optimizer moments are bf16.
        return jax.checkpoint_policies.save_only_these_names(
            "qkv", "attn_proj", "mlp_proj", "flash_o", "flash_lse")
    if name == "dots_flash_fc":
        # dots_flash but trading qkv (3E, 6-unit recompute) for mlp_fc
        # (4E, 8-unit recompute): less backward recompute per byte saved.
        # Needs grad_dtype=bf16's memory headroom at bs8/16 GB.
        return jax.checkpoint_policies.save_only_these_names(
            "attn_proj", "mlp_fc", "mlp_proj", "flash_o", "flash_lse")
    if name == "dots_plus":
        # everything "dots" keeps plus the flash residuals: no matmul or
        # attention recompute at all in backward. The roomiest policy;
        # needs bf16 grads to fit 16 GB at GPT-2-large/bs8.
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "flash_o", "flash_lse"))
    if name == "dots_flash_fc_lean":
        # dots_flash_fc minus attn_proj: with flash_o saved, re-deriving
        # the attention projection is ONE matmul from a saved input
        # (~2/24 of forward flops) — 1E/layer of HBM back for near-zero
        # recompute. Matters when optimizer state crowds the 16 GB chip.
        return jax.checkpoint_policies.save_only_these_names(
            "mlp_fc", "mlp_proj", "flash_o", "flash_lse")
    if name == "projs":
        # save only the residual-branch projections (2E per layer): qkv and
        # fc recompute in backward (~58% of forward flops) but the big-batch
        # µbatch that feeds the MXU at full tilt fits in 16 GB — measured
        # faster end-to-end than any fuller policy at a smaller batch.
        return jax.checkpoint_policies.save_only_these_names(
            "attn_proj", "mlp_proj")
    if name == "offload":
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    raise ValueError(f"unknown remat_policy {name!r}")


def _maybe_remat(cfg):
    if not cfg.remat:
        return Block
    return nn.remat(Block, prevent_cse=False, static_argnums=(2,),
                    policy=_remat_policy(cfg.remat_policy))


class ScanBody(nn.Module):
    """One scanned layer step: returns (carry, None) as nn.scan requires."""
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic, keep_prob):
        block = _maybe_remat(self.config)
        return block(self.config, name="blk")(x, deterministic, keep_prob), None


class GPT2LMHeadModel(nn.Module):
    config: GPT2Config

    @property
    def prefetch_layer_subtree(self):
        """Name of the layer-stacked params subtree the engine's
        stage3_prefetch pipeline may drive layer-by-layer, or None when
        the model can't offer one (unrolled layers have per-layer
        subtrees; MoE sows aux losses the functional twin doesn't
        collect; dropout needs per-layer rng plumbing)."""
        cfg = self.config
        if cfg.scan_layers and not cfg.moe_experts and cfg.dropout == 0:
            return "h"
        return None

    @nn.nowrap
    def prefetch_apply(self, params, input_ids, layer_scan,
                       deterministic=True, keep_prob=1.0, labels=None):
        """Functional twin of ``__call__`` (scan_layers path) where the
        transformer stack runs through ``layer_scan(body, x,
        params["h"])`` — the engine passes the double-buffered
        parameter-gather scan (parallel/prefetch.py) so each layer's
        shards gather one layer ahead of use. ``body(x, layer_params)``
        applies ONE block from an (unstacked) per-layer param tree.
        Numerics are pinned to ``__call__`` by tests/test_prefetch.py."""
        cfg = self.config
        S = input_ids.shape[1]
        x = _embed_lookup(params["wte"], input_ids).astype(cfg.dtype) \
            + params["wpe"][:S].astype(cfg.dtype)[None]

        scan_body = ScanBody(cfg)

        def body(xc, layer_params):
            y, _ = scan_body.apply({"params": layer_params}, xc,
                                   deterministic, keep_prob)
            return y

        x = layer_scan(body, x, params["h"])
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype).apply(
            {"params": params["ln_f"]}, x)
        if labels is not None and cfg.loss_chunk > 0 \
                and cfg.tie_word_embeddings:
            return chunked_lm_loss(x, params["wte"].astype(cfg.dtype),
                                   labels, cfg.loss_chunk)
        if cfg.tie_word_embeddings:
            logits = jnp.einsum("bse,ve->bsv", x,
                                params["wte"].astype(cfg.dtype))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype).apply(
                {"params": params["lm_head"]}, x)
        if labels is not None:
            return lm_loss(logits, labels)
        return logits

    @property
    def supports_collective_matmul(self):
        """The Blocks' projection layers (c_attn/c_proj/c_fc/c_proj) are
        CollectiveDense: under the prefetch pipeline's ``fused_matmul``
        gather mode they consume ZeRO-3 resting shards through the
        tile-granular fused kernels instead of a gathered full weight.
        The engine checks this marker before leaving shards in the
        layer tree — a model without it would crash on the shard shape."""
        return True

    @property
    def collective_matmul_paths(self):
        """Per-leaf whitelist backing ``supports_collective_matmul``:
        '/'-joined path SUFFIXES (within a per-layer param tree) of the
        kernels whose consuming module is CollectiveDense. The engine
        streams shards ONLY to these leaves — a 3D ``kernel`` param
        consumed by a plain nn.Dense elsewhere in the block must not be
        handed a shard (flax's declared-param shape check would reject
        it at trace time with an opaque error)."""
        return ("attn/c_attn/kernel", "attn/c_proj/kernel",
                "mlp/c_fc/kernel", "mlp/c_proj/kernel")

    @property
    def sparse_grad_params(self):
        """Leaves eligible for the engine's row-sparse gradient exchange
        (sparse_gradients config). Only the UNTIED input embedding
        qualifies: a tied LM head adds a dense d(logits)/d(wte) term
        touching every vocabulary row, so compressing would drop real
        gradient."""
        return () if self.config.tie_word_embeddings else ("wte",)

    @nn.compact
    def __call__(self, input_ids, deterministic=True, keep_prob=1.0,
                 labels=None):
        cfg = self.config
        B, S = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.n_positions, cfg.n_embd), cfg.param_dtype)
        pos = wpe[:S]
        mesh = _gspmd_mesh()
        if mesh is not None:
            # pin the position slice replicated AT THE PARAM EDGE (fp32,
            # before the cast/broadcast): GSPMD otherwise propagates the
            # batch sharding onto the broadcast's size-1 leading dim and
            # then cannot reshard to the TP'd wpe gradient's layout without
            # an involuntary full rematerialization — a whole-tensor
            # broadcast inside every step on a real mesh
            from jax.sharding import NamedSharding, PartitionSpec
            pos = jax.lax.with_sharding_constraint(
                pos, NamedSharding(mesh, PartitionSpec()))
        posb = pos.astype(cfg.dtype)[None]
        from deepspeed_tpu.parallel import mesh as mesh_lib
        if mesh is not None and \
                mesh.shape.get(mesh_lib.EXPERT_AXIS, 1) > 1:
            # the broadcast's size-1 leading dim otherwise inherits the
            # batch sharding; on expert meshes that degenerate
            # ('data','expert')-pair tiling is unconvertible to the wpe
            # gradient's model-axis layout and remats (same family as
            # the fp32 pin above — this one anchors the POST-cast/
            # broadcast edge both directions; other meshes convert fine
            # and skip the extra node)
            from jax.sharding import NamedSharding, PartitionSpec
            posb = jax.lax.with_sharding_constraint(
                posb, NamedSharding(mesh, PartitionSpec()))
        x = _embed_lookup(wte, input_ids).astype(cfg.dtype) + posb
        x = _carry_pin(x)

        if cfg.scan_layers:
            scanned = nn.scan(ScanBody,
                              variable_axes={"params": 0, "losses": 0},
                              split_rngs={"params": True, "dropout": True},
                              in_axes=(nn.broadcast, nn.broadcast),
                              length=cfg.n_layer,
                              unroll=max(1, cfg.scan_unroll))
            x, _ = scanned(cfg, name="h")(x, deterministic, keep_prob)
        else:
            block = _maybe_remat(cfg)
            for i in range(cfg.n_layer):
                x = block(cfg, name=f"h_{i}")(x, deterministic, keep_prob)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln_f")(x)
        if labels is not None and cfg.loss_chunk > 0 \
                and cfg.tie_word_embeddings:
            return chunked_lm_loss(x, wte.astype(cfg.dtype), labels,
                                   cfg.loss_chunk)
        if cfg.tie_word_embeddings:
            logits = jnp.einsum("bse,ve->bsv", x, wte.astype(cfg.dtype))
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype, name="lm_head")(x)
        if labels is not None:
            return lm_loss(logits, labels)
        return logits


def chunked_lm_loss(hidden, wte, labels, chunk, ignore_index=-100):
    """Fused LM head + next-token cross entropy without a [B, S, V] buffer.

    Scans over chunks of ``chunk`` tokens; each chunk projects [C, E] @
    [E, V] and reduces to per-token nll immediately. The chunk body is
    rematerialized, so backward recomputes each chunk's logits instead of
    saving them — one extra head matmul per step (~1-2% of model flops)
    buys back >1 GB of f32 logsumexp temporaries at GPT-2 vocab sizes.

    Matches ``lm_loss(logits, labels)`` to fp32 rounding: same shift, same
    ignore_index masking, same mean normalization.
    """
    B, S, E = hidden.shape
    xs = hidden[:, :-1, :].reshape(-1, E)
    tgt = labels[:, 1:].reshape(-1)
    n = xs.shape[0]
    pad = (-n) % chunk
    if pad:
        xs = jnp.pad(xs, ((0, pad), (0, 0)))
        tgt = jnp.pad(tgt, (0, pad), constant_values=ignore_index)
    xs = xs.reshape(-1, chunk, E)
    tgt = tgt.reshape(-1, chunk)

    @jax.checkpoint
    def chunk_nll(h, t):
        logits = (h @ wte.T).astype(jnp.float32)       # [C, V]
        valid = t != ignore_index
        t0 = jnp.where(valid, t, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        g = jnp.take_along_axis(logits, t0[:, None], axis=-1)[:, 0]
        return (jnp.sum(jnp.where(valid, lse - g, 0.0)),
                jnp.sum(valid.astype(jnp.int32)))

    def body(carry, xt):
        total, count = carry
        ds, dc = chunk_nll(*xt)
        return (total + ds, count + dc), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (xs, tgt))
    return total / jnp.maximum(count, 1)


def lm_loss(logits, labels, ignore_index=-100):
    """Next-token cross entropy in fp32. ``labels`` must be the UNSHIFTED
    token ids (typically ``labels is input_ids``); the shift happens here
    (logits[:, :-1] vs labels[:, 1:]). Do not pre-shift."""
    logits = logits[:, :-1].astype(jnp.float32)
    targets = labels[:, 1:]
    valid = targets != ignore_index
    targets = jnp.where(valid, targets, 0)
    # -log p(target) = logsumexp(logits) - logits[target]; this form never
    # materializes a [B, S, V] fp32 log-softmax in HBM (the lse and the
    # gathered target logit are both [B, S])
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, lse - tgt, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)


# -- presets ---------------------------------------------------------------

def gpt2_tiny(**kw):
    base = dict(vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=2)
    base.update(kw)
    return GPT2Config(**base)


def gpt2_small(**kw):
    return GPT2Config(n_embd=768, n_layer=12, n_head=12, **kw)


def gpt2_medium(**kw):
    return GPT2Config(n_embd=1024, n_layer=24, n_head=16, **kw)


def gpt2_large(**kw):
    return GPT2Config(n_embd=1280, n_layer=36, n_head=20, **kw)


def gpt2_xl(**kw):
    """The 1.5B north-star config (SURVEY §6: 48L/1600h)."""
    return GPT2Config(n_embd=1600, n_layer=48, n_head=25, **kw)
