from deepspeed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHeadModel,
    gpt2_tiny,
    gpt2_small,
    gpt2_medium,
    gpt2_large,
    gpt2_xl,
)
from deepspeed_tpu.models.bert import (
    BertConfig,
    BertModel,
    BertForPreTraining,
    BertForQuestionAnswering,
    BertForSequenceClassification,
    bert_tiny,
    bert_base,
    bert_large,
)
from deepspeed_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_tiny,
    llama_7b,
    llama3_8b,
    from_hf_llama,
    llama_generate,
)
