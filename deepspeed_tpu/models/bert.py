"""BERT family — the reference's fused-kernel showcase model.

The reference carries two full in-tree BERT implementations for kernel tests
(tests/unit/modeling.py post-LN, tests/unit/modelingpreln.py pre-LN, ~2.5k LoC)
and drives BERT pretraining/SQuAD e2e (tests/model/BingBertSquad). Here BERT
is a first-class model built directly on the fused encoder layer
(deepspeed_tpu/ops/transformer), with the same TPU idioms as GPT-2:
bf16 compute / fp32 params, optional nn.scan over layers, remat via the
transformer config's memory knobs.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
    transformer_layer,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = False       # modeling.py vs modelingpreln.py
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = False
    # fused-layer memory knobs (reference DeepSpeedTransformerConfig)
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    attn_dropout_checkpoint: bool = False
    # block-sparse attention layout (SparseAttentionUtils.sparse_config_for)
    sparsity_config: Any = None

    def transformer_config(self) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            sparsity_config=self.sparsity_config,
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_attention_heads,
            attn_dropout_ratio=self.attention_probs_dropout_prob,
            hidden_dropout_ratio=self.hidden_dropout_prob,
            num_hidden_layers=self.num_hidden_layers,
            initializer_range=self.initializer_range,
            layer_norm_eps=self.layer_norm_eps,
            pre_layer_norm=self.pre_layer_norm,
            normalize_invertible=self.normalize_invertible,
            gelu_checkpoint=self.gelu_checkpoint,
            attn_dropout_checkpoint=self.attn_dropout_checkpoint,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )

    def num_params(self):
        E, L, F = self.hidden_size, self.num_hidden_layers, self.intermediate_size
        emb = (self.vocab_size + self.max_position_embeddings
               + self.type_vocab_size) * E + 2 * E
        per_layer = 4 * E * E + 2 * E * F + 9 * E + F
        final_ln = 2 * E if self.pre_layer_norm else 0
        return emb + L * per_layer + final_ln + E * E + E


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, deterministic=True):
        cfg = self.config
        B, S = input_ids.shape
        init = nn.initializers.normal(cfg.initializer_range)
        word = self.param("word_embeddings", init,
                          (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        pos = self.param("position_embeddings", init,
                         (cfg.max_position_embeddings, cfg.hidden_size),
                         cfg.param_dtype)
        tok = self.param("token_type_embeddings", init,
                         (cfg.type_vocab_size, cfg.hidden_size),
                         cfg.param_dtype)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = word[input_ids] + pos[None, :S] + tok[token_type_ids]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="LayerNorm")(
            x.astype(cfg.dtype))
        if cfg.hidden_dropout_prob > 0:
            x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic)
        return x


class _ScanLayer(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask, deterministic):
        layer = transformer_layer(self.config.transformer_config())
        return layer(x, attention_mask, deterministic), None


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic=True):
        cfg = self.config
        if cfg.scan_layers:
            scanned = nn.scan(_ScanLayer,
                              variable_axes={"params": 0},
                              split_rngs={"params": True, "dropout": True},
                              in_axes=(nn.broadcast, nn.broadcast),
                              length=cfg.num_hidden_layers)
            x, _ = scanned(cfg, name="layer")(x, attention_mask, deterministic)
        else:
            for i in range(cfg.num_hidden_layers):
                x = transformer_layer(cfg.transformer_config())(
                    x, attention_mask, deterministic)
        if cfg.pre_layer_norm:
            # pre-LN stacks need a final normalize (modelingpreln.py ditto)
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="FinalLayerNorm")(x)
        return x


class BertModel(nn.Module):
    """Backbone: embeddings → fused encoder stack → pooler.

    Returns (sequence_output [B,S,E], pooled_output [B,E])."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        x = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, deterministic)
        x = BertEncoder(cfg, name="encoder")(x, attention_mask, deterministic)
        pooled = nn.tanh(nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name="pooler")(x[:, 0]))
        return x, pooled


class BertForPreTraining(nn.Module):
    """MLM + NSP heads; returns (prediction_logits, seq_relationship_logits).
    The MLM decoder is tied to the word embeddings (standard BERT; the
    reference's BertPreTrainingHeads in tests/unit/modeling.py). Weight tying
    uses the setup-submodule `.variables` idiom so the decoder reads the live
    embedding table instead of duplicating the [V, E] matrix."""
    config: BertConfig

    def setup(self):
        cfg = self.config
        self.bert = BertModel(cfg)
        self.transform = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.normal(cfg.initializer_range))
        self.transform_ln = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype)
        self.seq_relationship = nn.Dense(
            2, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        self.mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                                   (cfg.vocab_size,), cfg.param_dtype)

    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        cfg = self.config
        seq_out, pooled = self.bert(input_ids, attention_mask, token_type_ids,
                                    deterministic)
        h = self.transform(seq_out)
        h = nn.gelu(h, approximate=False)
        h = self.transform_ln(h)
        word_emb = self.bert.variables["params"]["embeddings"][
            "word_embeddings"]
        mlm_logits = jnp.einsum("bse,ve->bsv", h,
                                word_emb.astype(cfg.dtype)) \
            + self.mlm_bias.astype(cfg.dtype)
        nsp_logits = self.seq_relationship(pooled)
        return mlm_logits, nsp_logits


class BertForQuestionAnswering(nn.Module):
    """SQuAD head (reference e2e: tests/model/BingBertSquad).
    Returns (start_logits, end_logits)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        seq_out, _ = BertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic)
        logits = nn.Dense(2, dtype=jnp.float32,
                          param_dtype=self.config.param_dtype,
                          name="qa_outputs")(seq_out.astype(jnp.float32))
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]


class BertForSequenceClassification(nn.Module):
    config: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic=True):
        _, pooled = BertModel(self.config, name="bert")(
            input_ids, attention_mask, token_type_ids, deterministic)
        return nn.Dense(self.num_labels, dtype=jnp.float32,
                        param_dtype=self.config.param_dtype,
                        name="classifier")(pooled.astype(jnp.float32))


def mlm_loss(mlm_logits, labels, ignore_index=-100):
    """Masked-LM cross entropy in fp32 over positions where labels != ignore."""
    logits = mlm_logits.astype(jnp.float32)
    valid = labels != ignore_index
    targets = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ll = jnp.where(valid, ll, 0.0)
    return -ll.sum() / jnp.maximum(valid.sum(), 1)


def pretraining_loss(outputs, batch):
    """Combined MLM + NSP loss from a batch dict with keys
    input_ids/attention_mask/token_type_ids/mlm_labels[/nsp_labels]."""
    mlm_logits, nsp_logits = outputs
    loss = mlm_loss(mlm_logits, batch["mlm_labels"])
    if "nsp_labels" in batch:
        nsp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)
        nsp_ll = jnp.take_along_axis(
            nsp, batch["nsp_labels"][:, None], axis=-1)[:, 0]
        loss = loss - nsp_ll.mean()
    return loss


# -- presets ---------------------------------------------------------------

def bert_tiny(**kw):
    base = dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                num_attention_heads=2, intermediate_size=128,
                max_position_embeddings=128)
    base.update(kw)
    return BertConfig(**base)


def bert_base(**kw):
    return BertConfig(**kw)


def bert_large(**kw):
    base = dict(hidden_size=1024, num_hidden_layers=24,
                num_attention_heads=16, intermediate_size=4096)
    base.update(kw)
    return BertConfig(**base)
