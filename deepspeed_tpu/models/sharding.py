"""Model-specific tensor-parallel sharding rules.

The reference delegated TP math to Megatron via the `mpu` object (SURVEY
§2.3); on TPU TP is just PartitionSpecs over the 'model' mesh axis — XLA
splits the matmuls and inserts the psums. These rules give Megatron-style
column/row parallel layouts for the in-tree models.
"""

from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import MODEL_AXIS


def _gpt2_leaf_spec(path_names, shape):
    """Megatron TP layout:
      c_attn / c_fc kernels  → column parallel (shard output dim)
      c_proj kernels         → row parallel (shard input dim)
      wte                    → vocab parallel
      layernorm, biases of row-parallel, wpe → replicated
    Works for both scanned params (leading layer dim) and per-layer trees.
    """
    name = path_names[-1]
    parent = path_names[-2] if len(path_names) >= 2 else ""
    ndim = len(shape)

    def spec_last(axis_name):
        return P(*([None] * (ndim - 1) + [axis_name]))

    def spec_dim(d, axis_name):
        s = [None] * ndim
        s[d] = axis_name
        return P(*s)

    if name == "wte":
        return spec_dim(0, MODEL_AXIS)
    if name == "wpe":
        return P(*([None] * ndim))
    if parent in ("c_attn", "c_fc"):
        # column parallel: kernel [.., in, out] shard out; bias [.., out] shard out
        return spec_last(MODEL_AXIS)
    if parent == "c_proj" and name == "kernel":
        # row parallel: shard the contracting (second-to-last) dim
        return spec_dim(ndim - 2, MODEL_AXIS)
    return P(*([None] * ndim))


def gpt2_tp_specs(params):
    """PartitionSpec tree matching a GPT2LMHeadModel params tree."""
    return _walk_specs(params, _gpt2_leaf_spec)


def _bert_leaf_spec(path_names, shape):
    """Megatron TP layout for the fused BERT encoder
    (ops/transformer/transformer.py param names):
      attn_qkvw / inter_w kernels+biases → column parallel
      attn_ow / output_w kernels         → row parallel
      word_embeddings                    → vocab parallel
      layernorms, heads, position/token-type embeddings → replicated
    """
    name = path_names[-1]
    parent = path_names[-2] if len(path_names) >= 2 else ""
    ndim = len(shape)
    if name == "word_embeddings":
        s = [None] * ndim
        s[0] = MODEL_AXIS
        return P(*s)
    if parent in ("attn_qkvw", "inter_w"):
        return P(*([None] * (ndim - 1) + [MODEL_AXIS]))
    if parent in ("attn_ow", "output_w") and name == "kernel":
        s = [None] * ndim
        s[ndim - 2] = MODEL_AXIS
        return P(*s)
    return P(*([None] * ndim))


def _walk_specs(params, leaf_fn):
    def walk(tree, path):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        return leaf_fn(path, tree.shape)
    return walk(params, ())


# -- registry ---------------------------------------------------------------
# Maps model CLASS NAMES (strings, so model modules need not import here)
# to leaf-spec functions. The engine consults this when the mesh has a
# model axis and the model doesn't expose param_partition_specs itself —
# the replacement for the reference's delegation to an external Megatron
# `mpu` (SURVEY §2.3).

_TP_RULES = {
    "GPT2LMHeadModel": _gpt2_leaf_spec,
    "BertModel": _bert_leaf_spec,
    "BertForPreTraining": _bert_leaf_spec,
    "BertForQuestionAnswering": _bert_leaf_spec,
    "BertForSequenceClassification": _bert_leaf_spec,
}


def register_tp_rules(model_cls_or_name, leaf_fn):
    """Register Megatron-style sharding rules for a model class:
    leaf_fn(path_names, shape) -> PartitionSpec. Accepts the class or its
    name. User models can also just expose `param_partition_specs`."""
    name = model_cls_or_name if isinstance(model_cls_or_name, str) \
        else model_cls_or_name.__name__
    _TP_RULES[name] = leaf_fn


def tp_specs_for(model, params):
    """Resolve registered TP rules for `model` over a params(-shapes) tree;
    None when no rules are registered for its class (or bases)."""
    for cls in type(model).__mro__:
        fn = _TP_RULES.get(cls.__name__)
        if fn is not None:
            return _walk_specs(params, fn)
    return None
