"""Model-specific tensor-parallel sharding rules.

The reference delegated TP math to Megatron via the `mpu` object (SURVEY
§2.3); on TPU TP is just PartitionSpecs over the 'model' mesh axis — XLA
splits the matmuls and inserts the psums. These rules give Megatron-style
column/row parallel layouts for the in-tree models.
"""

from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import MODEL_AXIS


def _gpt2_leaf_spec(path_names, shape):
    """Megatron TP layout:
      c_attn / c_fc kernels  → column parallel (shard output dim)
      c_proj kernels         → row parallel (shard input dim)
      wte                    → vocab parallel
      layernorm, biases of row-parallel, wpe → replicated
    Works for both scanned params (leading layer dim) and per-layer trees.
    """
    name = path_names[-1]
    parent = path_names[-2] if len(path_names) >= 2 else ""
    ndim = len(shape)

    def spec_last(axis_name):
        return P(*([None] * (ndim - 1) + [axis_name]))

    def spec_dim(d, axis_name):
        s = [None] * ndim
        s[d] = axis_name
        return P(*s)

    if name == "wte":
        return spec_dim(0, MODEL_AXIS)
    if name == "wpe":
        return P(*([None] * ndim))
    if parent in ("c_attn", "c_fc"):
        # column parallel: kernel [.., in, out] shard out; bias [.., out] shard out
        return spec_last(MODEL_AXIS)
    if parent == "c_proj" and name == "kernel":
        # row parallel: shard the contracting (second-to-last) dim
        return spec_dim(ndim - 2, MODEL_AXIS)
    return P(*([None] * ndim))


def gpt2_tp_specs(params):
    """PartitionSpec tree matching a GPT2LMHeadModel params tree."""
    def walk(tree, path):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        return _gpt2_leaf_spec(path, tree.shape)
    return walk(params, ())
