"""HuggingFace interop — load HF GPT-2 and BERT checkpoints into the TPU
framework.

The reference consumes HF/Megatron models by module surgery
(module_inject/replace_module.py) and by Megatron checkpoint resharding
(runtime/state_dict_factory.py:272). The flax equivalents here are pure
pytree converters: HF Flax GPT-2 params → `GPT2LMHeadModel` params and HF
Flax BERT params → `BertModel` params (either unrolled or scan-stacked
layout), plus config translation — so a user can bring an HF checkpoint
and train it under ZeRO/offload/1-bit or serve it through the fused
inference stack (`models/gpt2_inference.py`). The BERT path doubles as a
numerics cross-check of the fused encoder layer against transformers'
independent implementation.
"""

from typing import Any

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config


def config_from_hf_gpt2(hf_config, **overrides) -> GPT2Config:
    """transformers.GPT2Config → GPT2Config. GPT-2's activation is the tanh
    GELU in both stacks; dtype/remat/scan knobs come from ``overrides``."""
    base = dict(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        dropout=getattr(hf_config, "resid_pdrop", 0.0),
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", True),
    )
    base.update(overrides)
    return GPT2Config(**base)


def _dense(conv1d):
    """HF flax GPT-2 keeps torch Conv1D orientation: kernel [out, in].
    nn.Dense wants [in, out]."""
    return {"kernel": jnp.asarray(conv1d["kernel"]).T,
            "bias": jnp.asarray(conv1d["bias"])}


def _hf_layer(block):
    """One HF flax GPT-2 block subtree → our Block subtree."""
    return {
        "ln_1": dict(block["ln_1"]),
        "attn": {"c_attn": _dense(block["attn"]["c_attn"]),
                 "c_proj": _dense(block["attn"]["c_proj"])},
        "ln_2": dict(block["ln_2"]),
        "mlp": {"c_fc": _dense(block["mlp"]["c_fc"]),
                "c_proj": _dense(block["mlp"]["c_proj"])},
    }


def convert_hf_gpt2_params(hf_params, cfg: GPT2Config):
    """HF FlaxGPT2LMHeadModel params → our GPT2LMHeadModel params.

    Accepts the params dict with or without the top-level "transformer"
    wrapper. Produces the layout matching ``cfg.scan_layers`` (scan-stacked
    leaves under h/blk, or h_0..h_{L-1})."""
    p = hf_params.get("transformer", hf_params)
    out = {
        "wte": jnp.asarray(p["wte"]["embedding"]),
        "wpe": jnp.asarray(p["wpe"]["embedding"]),
        "ln_f": dict(p["ln_f"]),
    }
    blocks = [_hf_layer(p["h"][str(i)]) for i in range(cfg.n_layer)]
    if cfg.scan_layers:
        out["h"] = {"blk": _stack_layers(blocks)}
    else:
        for i, blk in enumerate(blocks):
            out[f"h_{i}"] = blk
    if not cfg.tie_word_embeddings and "lm_head" in hf_params:
        out["lm_head"] = dict(hf_params["lm_head"])
    return out


def from_hf_gpt2(hf_model, **config_overrides):
    """(our_config, our_params) from a transformers FlaxGPT2LMHeadModel."""
    cfg = config_from_hf_gpt2(hf_model.config, **config_overrides)
    return cfg, convert_hf_gpt2_params(hf_model.params, cfg)


# ----------------------------------------------------------------- BERT

def _stack_layers(layers):
    """Per-layer subtrees → one subtree with a leading [L] axis (the
    nn.scan parameter layout)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *layers)


def config_from_hf_bert(hf_config, **overrides):
    """transformers.BertConfig → models.bert.BertConfig (post-LN, exact
    GELU on both sides)."""
    from deepspeed_tpu.models.bert import BertConfig
    act = getattr(hf_config, "hidden_act", "gelu")
    if act != "gelu":
        raise ValueError(
            f"hidden_act={act!r} is not convertible: the fused encoder "
            f"layer computes exact GELU (transformer.py nn.gelu "
            f"approximate=False); converting would silently change every "
            f"FFN activation")
    pos = getattr(hf_config, "position_embedding_type", "absolute")
    if pos != "absolute":
        raise ValueError(
            f"position_embedding_type={pos!r} is not convertible: the "
            f"rebuild uses learned absolute position embeddings")
    base = dict(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_hidden_layers=hf_config.num_hidden_layers,
        num_attention_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        hidden_dropout_prob=hf_config.hidden_dropout_prob,
        attention_probs_dropout_prob=hf_config.attention_probs_dropout_prob,
        layer_norm_eps=hf_config.layer_norm_eps,
    )
    base.update(overrides)
    return BertConfig(**base)


def _hf_bert_layer(layer):
    """One HF flax BERT encoder layer → our fused-layer subtree: separate
    q/k/v Denses concatenate into attn_qkvw (the fused layer splits in
    q,k,v order along the out axis — the reference's qkvw packing,
    replace_module.py:34-41)."""
    att = layer["attention"]
    qkv_k = jnp.concatenate(
        [jnp.asarray(att["self"][n]["kernel"]) for n in
         ("query", "key", "value")], axis=1)
    qkv_b = jnp.concatenate(
        [jnp.asarray(att["self"][n]["bias"]) for n in
         ("query", "key", "value")])
    return {
        "attn_qkvw": {"kernel": qkv_k, "bias": qkv_b},
        "attn_ow": dict(att["output"]["dense"]),
        "attn_nw": dict(att["output"]["LayerNorm"]),
        "inter_w": dict(layer["intermediate"]["dense"]),
        "output_w": dict(layer["output"]["dense"]),
        "norm_w": dict(layer["output"]["LayerNorm"]),
    }


def convert_hf_bert_params(hf_params, cfg):
    """HF FlaxBertModel params → our BertModel params (unrolled or
    scan-stacked per ``cfg.scan_layers``)."""
    p = hf_params.get("params", hf_params)
    emb = p["embeddings"]
    out = {
        "embeddings": {
            "word_embeddings": jnp.asarray(
                emb["word_embeddings"]["embedding"]),
            "position_embeddings": jnp.asarray(
                emb["position_embeddings"]["embedding"]),
            "token_type_embeddings": jnp.asarray(
                emb["token_type_embeddings"]["embedding"]),
            "LayerNorm": dict(emb["LayerNorm"]),
        },
        "pooler": dict(p["pooler"]["dense"]),
    }
    layers = [_hf_bert_layer(p["encoder"]["layer"][str(i)])
              for i in range(cfg.num_hidden_layers)]
    if cfg.scan_layers:
        out["encoder"] = {
            "layer": {"DeepSpeedTransformerLayer_0": _stack_layers(layers)}}
    else:
        out["encoder"] = {
            f"DeepSpeedTransformerLayer_{i}": layers[i]
            for i in range(cfg.num_hidden_layers)}
    return out


def from_hf_bert(hf_model, **config_overrides):
    """(our_config, our_params) from a transformers FlaxBertModel."""
    cfg = config_from_hf_bert(hf_model.config, **config_overrides)
    return cfg, convert_hf_bert_params(hf_model.params, cfg)
