"""HuggingFace interop — load HF GPT-2 checkpoints into the TPU framework.

The reference consumes HF/Megatron models by module surgery
(module_inject/replace_module.py) and by Megatron checkpoint resharding
(runtime/state_dict_factory.py:272). The flax equivalents here are pure
pytree converters: HF Flax GPT-2 params → `GPT2LMHeadModel` params (either
unrolled or scan-stacked layout), plus config translation — so a user can
bring an HF GPT-2 and train it under ZeRO/offload/1-bit or serve it through
the fused inference stack (`models/gpt2_inference.py`).
"""

from typing import Any

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config


def config_from_hf_gpt2(hf_config, **overrides) -> GPT2Config:
    """transformers.GPT2Config → GPT2Config. GPT-2's activation is the tanh
    GELU in both stacks; dtype/remat/scan knobs come from ``overrides``."""
    base = dict(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        dropout=getattr(hf_config, "resid_pdrop", 0.0),
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", True),
    )
    base.update(overrides)
    return GPT2Config(**base)


def _dense(conv1d):
    """HF flax GPT-2 keeps torch Conv1D orientation: kernel [out, in].
    nn.Dense wants [in, out]."""
    return {"kernel": jnp.asarray(conv1d["kernel"]).T,
            "bias": jnp.asarray(conv1d["bias"])}


def _hf_layer(block):
    """One HF flax GPT-2 block subtree → our Block subtree."""
    return {
        "ln_1": dict(block["ln_1"]),
        "attn": {"c_attn": _dense(block["attn"]["c_attn"]),
                 "c_proj": _dense(block["attn"]["c_proj"])},
        "ln_2": dict(block["ln_2"]),
        "mlp": {"c_fc": _dense(block["mlp"]["c_fc"]),
                "c_proj": _dense(block["mlp"]["c_proj"])},
    }


def convert_hf_gpt2_params(hf_params, cfg: GPT2Config):
    """HF FlaxGPT2LMHeadModel params → our GPT2LMHeadModel params.

    Accepts the params dict with or without the top-level "transformer"
    wrapper. Produces the layout matching ``cfg.scan_layers`` (scan-stacked
    leaves under h/blk, or h_0..h_{L-1})."""
    p = hf_params.get("transformer", hf_params)
    out = {
        "wte": jnp.asarray(p["wte"]["embedding"]),
        "wpe": jnp.asarray(p["wpe"]["embedding"]),
        "ln_f": dict(p["ln_f"]),
    }
    blocks = [_hf_layer(p["h"][str(i)]) for i in range(cfg.n_layer)]
    if cfg.scan_layers:
        out["h"] = {"blk": jax.tree_util.tree_map(
            lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *blocks)}
    else:
        for i, blk in enumerate(blocks):
            out[f"h_{i}"] = blk
    if not cfg.tie_word_embeddings and "lm_head" in hf_params:
        out["lm_head"] = dict(hf_params["lm_head"])
    return out


def from_hf_gpt2(hf_model, **config_overrides):
    """(our_config, our_params) from a transformers FlaxGPT2LMHeadModel."""
    cfg = config_from_hf_gpt2(hf_model.config, **config_overrides)
    return cfg, convert_hf_gpt2_params(hf_model.params, cfg)
