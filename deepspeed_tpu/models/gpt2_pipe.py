"""GPT-2 with pipeline-parallel blocks — the in-tree model the reference
expresses as PipelineModule(LayerSpec(GPT2Block)...) (pipe/module.py:87).

Embedding and LM head run outside the pipeline (replicated w.r.t. the pipe
axis, sharded over data/model as usual); the L transformer blocks are
stage-stacked [S, L/S, ...], sharded over the 'pipe' mesh axis, and executed
by the 1F1B SPMD pipeline (parallel/pipeline_1f1b.py). Composes with ZeRO
(data axis) and TP (model axis) since the pipeline shard_maps only the
pipe axis.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel, Block
from deepspeed_tpu.models.sharding import _gpt2_leaf_spec
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel.pipeline_1f1b import (
    pipeline_1f1b, pipeline_infer, stack_stage_params)
from jax.sharding import PartitionSpec as P


class GPT2PipeModel:
    """flax-like (init/apply) wrapper. ``num_microbatches`` is the pipeline
    µbatch count (the reference's engine.micro_batches,
    pipe/engine.py:105); must divide the batch size."""

    def __init__(self, config: GPT2Config, mesh, num_microbatches: Optional[int] = None):
        if not config.scan_layers:
            config = GPT2Config(**{**config.__dict__, "scan_layers": True})
        self.config = config
        self.mesh = mesh
        self.num_stages = mesh_lib.mesh_axis_size(mesh, mesh_lib.PIPE_AXIS)
        assert config.n_layer % max(self.num_stages, 1) == 0, (
            f"n_layer={config.n_layer} must divide into {self.num_stages} stages")
        self.num_microbatches = num_microbatches or max(self.num_stages, 1)
        self._inner = GPT2LMHeadModel(config)
        self._infer_fn = None   # jitted inference apply, built on first use

    # introspection stub so the engine's loss-fn resolver sees the kwargs
    def __call__(self, input_ids, deterministic=True, keep_prob=1.0):
        raise RuntimeError("use .apply(variables, ...)")

    def init(self, rng, input_ids):
        variables = self._inner.init(rng, input_ids)
        params = dict(variables["params"])
        blocks = params.pop("h")["blk"]  # leaves [L, ...]
        params["h_stages"] = stack_stage_params(blocks, max(self.num_stages, 1))
        return {"params": params}

    def _unstacked(self, params):
        inner = dict(params)
        stages = inner.pop("h_stages")
        inner["h"] = {"blk": jax.tree_util.tree_map(
            lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]),
            stages)}
        return inner

    def apply(self, variables, input_ids, deterministic=True, keep_prob=1.0,
              inference=False):
        """``inference=True`` executes the forward-only InferenceSchedule
        program (parallel/pipeline_1f1b.py pipeline_infer) instead of the
        differentiable 1F1B run — the serving/eval path."""
        params = variables["params"]
        cfg = self.config
        B, T = input_ids.shape
        M = self.num_microbatches
        assert B % M == 0, (f"batch {B} not divisible by "
                            f"num_microbatches {M}")

        wte = params["wte"]
        wpe = params["wpe"]
        x = wte[input_ids].astype(cfg.dtype) + wpe[None, :T].astype(cfg.dtype)

        def stage_fn(stage_params, h):
            def one_layer(carry, layer_params):
                out = Block(cfg).apply({"params": layer_params}, carry,
                                       deterministic, keep_prob)
                return out, None
            h, _ = jax.lax.scan(one_layer, h, stage_params)
            return h

        mb = x.reshape((M, B // M) + x.shape[1:])
        run = pipeline_infer if inference else pipeline_1f1b
        h = run(stage_fn, params["h_stages"], mb, self.mesh)
        x = h.reshape(B, T, cfg.n_embd)

        from flax import linen as nn
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype).apply(
            {"params": params["ln_f"]}, x)
        logits = jnp.einsum("bse,ve->bsv", x, wte.astype(cfg.dtype))
        return logits

    def generate(self, variables, input_ids, max_new_tokens=8):
        """Greedy multi-stage decode through the InferenceSchedule program:
        each step pipelines the full-context forward (microbatches fill the
        stages) and writes the argmax token — the reference's
        pipelined-inference role (pipe/engine.py:1209 on
        InferenceSchedule). Logits must match the single-device model's
        re-forward decode exactly (tests/test_pipeline_1f1b.py).

        The context is right-padded to T + max_new_tokens up front so every
        step runs the SAME shape — one compilation for the whole decode
        (causal attention makes the not-yet-written positions inert)."""
        B, T = input_ids.shape
        ids = jnp.pad(input_ids, ((0, 0), (0, max_new_tokens)))
        if self._infer_fn is None:
            self._infer_fn = jax.jit(
                lambda v, x: self.apply(v, x, inference=True))
        for i in range(max_new_tokens):
            logits = self._infer_fn(variables, ids)
            nxt = jnp.argmax(logits[:, T + i - 1, :].astype(jnp.float32),
                             axis=-1)
            ids = ids.at[:, T + i].set(nxt.astype(ids.dtype))
        return ids

    def param_partition_specs(self, params_shapes):
        """Base sharding specs: 'pipe' on the stage dim of h_stages,
        Megatron TP axes elsewhere (consumed by the engine's
        ZeroPartitioner as base specs)."""
        def walk(tree, path):
            if isinstance(tree, dict):
                return {k: walk(v, path + (k,)) for k, v in tree.items()}
            spec = _gpt2_leaf_spec(path, tree.shape)
            if path and path[0] == "h_stages":
                spec = P(mesh_lib.PIPE_AXIS, *tuple(spec)[1:]) \
                    if len(spec) >= 1 else P(mesh_lib.PIPE_AXIS)
            return spec
        tree = params_shapes.get("params", params_shapes) \
            if isinstance(params_shapes, dict) else params_shapes
        return walk(tree, ())
