"""Elasticity config keys — reference elasticity/constants.py."""

ELASTICITY = "elasticity"

LATEST_ELASTICITY_VERSION = 0.1

ENABLED = "enabled"
ENABLED_DEFAULT = False

# Max acceptable train_batch_size
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000

# Acceptable micro batch sizes, same as train_micro_batch_size_per_gpu
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]

# Device-count search range. TPU spelling is primary; reference "gpus"
# spelling accepted for config parity.
MIN_CHIPS = "min_chips"
MAX_CHIPS = "max_chips"
MIN_GPUS = "min_gpus"
MAX_GPUS = "max_gpus"
MIN_CHIPS_DEFAULT = 1
MAX_CHIPS_DEFAULT = 10000

# Minimum running time (minutes) before the scheduler may rescale the job
MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0

PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

# If elastic mode is enabled, batch info outside the elastic section is
# ignored; this flag silences the error that otherwise raises.
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False

VERSION = "version"
VERSION_DEFAULT = LATEST_ELASTICITY_VERSION

# Minimum framework version supporting elasticity
MINIMUM_DEEPSPEED_VERSION = "0.1.0"

# Environment variable carrying the scheduler's view of the elastic config
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"
