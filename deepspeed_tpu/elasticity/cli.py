"""`dstpu_elastic` — rebuild of the reference's bin/ds_elastic CLI: given a
config with an `elasticity` block, print the computed final batch size,
valid chip counts, and (with --world-size) the micro-batch per chip."""

import argparse
import json

from deepspeed_tpu.elasticity import compute_elastic_config
from deepspeed_tpu.version import __version__


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="deepspeed_tpu config json")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="Intended/current number of chips")
    args = parser.parse_args(argv)

    with open(args.config) as fd:
        ds_config = json.load(fd)

    print("-" * 42)
    print("Elasticity config:")
    print("-" * 42)
    print(json.dumps(ds_config["elasticity"], indent=4, sort_keys=True))

    if args.world_size > 0:
        final_batch, valid_chips, micro_batch = compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version=__version__,
            world_size=args.world_size)
        print("-" * 42)
        print(f"Calculated results for world size {args.world_size}:")
        print("-" * 42)
        print(f"final_batch_size .... {final_batch}")
        print(f"valid_chips ......... {valid_chips}")
        print(f"micro_batch_size .... {micro_batch}")
    else:
        final_batch, valid_chips = compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version=__version__)
        print("-" * 42)
        print("Calculated results:")
        print("-" * 42)
        print(f"final_batch_size .... {final_batch}")
        print(f"valid_chips ......... {valid_chips}")


if __name__ == "__main__":
    main()
