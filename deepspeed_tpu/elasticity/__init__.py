from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_candidate_batch_sizes,
    get_valid_chip_counts,
    highly_composite_numbers,
)
from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
)
