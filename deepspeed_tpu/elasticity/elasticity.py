"""Elastic batch-size calculator — reference elasticity/elasticity.py.

Given a max acceptable train batch size, candidate micro-batch sizes, and a
chip-count range, pick one global batch size that factors as
``micro * grad_accum * world_size`` for as many world sizes as possible, so a
job rescheduled onto a different chip count keeps the same effective batch
(and therefore the same convergence behavior).

The reference hard-codes the first 38 highly composite numbers
(elasticity/elasticity.py:19); here the HCN ladder is generated from the
prime-factorization characterization (non-increasing exponents over the first
primes), which is exact and extends to any bound.
"""

import functools
import json
import math
import os
import re

from deepspeed_tpu.elasticity import constants as EC
from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.version import __version__

_HCN_PRIMES = (2, 3, 5, 7, 11, 13, 17, 19, 23)


@functools.lru_cache(maxsize=None)
def highly_composite_numbers(limit):
    """All highly composite numbers <= limit.

    Every HCN is a product of the first k primes with non-increasing
    exponents, so enumerating that (small) candidate set and keeping
    divisor-count record-setters is exact. Replaces the reference's
    hard-coded HCN_LIST (elasticity/elasticity.py:19-58).
    """
    candidates = []

    def extend(prime_idx, value, ndivisors, max_exp):
        candidates.append((value, ndivisors))
        if prime_idx == len(_HCN_PRIMES):
            return
        p = _HCN_PRIMES[prime_idx]
        v = value
        for exp in range(1, max_exp + 1):
            v *= p
            if v > limit:
                break
            extend(prime_idx + 1, v, ndivisors * (exp + 1), exp)

    extend(0, 1, 1, max(1, int(math.log2(max(limit, 2)))))
    candidates.sort()
    hcns, best = [], 0
    for value, ndiv in candidates:
        if ndiv > best:
            hcns.append(value)
            best = ndiv
    return hcns


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """For each base, the largest base*HCN <= max (reference
    elasticity/elasticity.py:61-75)."""
    hcns = highly_composite_numbers(max_acceptable_batch_size)
    candidates = set()
    for base in base_list:
        scaled = [base * h for h in hcns if base * h <= max_acceptable_batch_size]
        candidates.add(scaled[-1] if scaled else base)
    return sorted(candidates)


def get_valid_chip_counts(batch_size, micro_batches, min_chips, max_chips):
    """All world sizes w in [min, max] such that batch_size = micro * k * w
    for some micro-batch and integer k (reference elasticity/elasticity.py:78-93)."""
    valid = set()
    for micro in micro_batches:
        if batch_size % micro:
            continue
        slots = batch_size // micro
        # any divisor of slots is a usable world size (remainder = grad accum)
        for d in range(1, int(math.isqrt(slots)) + 1):
            if slots % d == 0:
                for w in (d, slots // d):
                    if min_chips <= w <= max_chips:
                        valid.add(w)
    return sorted(valid)


def _get_compatible_chips(micro_batches, max_acceptable_batch_size,
                          min_chips=None, max_chips=None, prefer_larger=True):
    """Pick the batch size with the most compatible chip counts (reference
    elasticity/elasticity.py:120-170, _get_compatible_gpus_v01)."""
    min_chips = min_chips or 1
    max_chips = max_chips or max_acceptable_batch_size // min(micro_batches)

    if not all(m <= max_acceptable_batch_size for m in micro_batches):
        raise ElasticityConfigError(
            f"All micro batches {micro_batches} must be <= "
            f"max_acceptable_batch_size {max_acceptable_batch_size}")

    lcm = functools.reduce(math.lcm, micro_batches)
    bases = list(micro_batches) + [lcm]

    best_batch, best_valid = min(micro_batches), []
    for batch_size in get_candidate_batch_sizes(bases, max_acceptable_batch_size):
        valid = get_valid_chip_counts(batch_size, micro_batches, min_chips, max_chips)
        better_count = len(valid) > len(best_valid)
        tie = len(valid) == len(best_valid)
        preferred = batch_size > best_batch if prefer_larger else batch_size < best_batch
        if better_count or (tie and preferred):
            best_batch, best_valid = batch_size, valid
    return int(best_batch), best_valid


def _parse_version(version_str):
    m = re.search(r"^(\d+)\.(\d+)(?:\.(\d+))?", version_str)
    if m is None:
        raise ElasticityError(
            f"Expecting major.minor[.patch] version format, got {version_str}")
    return int(m.group(1)), int(m.group(2)), int(m.group(3) or 0)


def _compatible_version_check(target_version):
    if _parse_version(target_version) < _parse_version(EC.MINIMUM_DEEPSPEED_VERSION):
        raise ElasticityError(
            f"Target version {target_version} is below minimum "
            f"{EC.MINIMUM_DEEPSPEED_VERSION} supporting elasticity.")
    return True


def elasticity_enabled(ds_config):
    """reference elasticity/elasticity.py:201."""
    if EC.ELASTICITY not in ds_config:
        return False
    return ds_config[EC.ELASTICITY].get(EC.ENABLED, EC.ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Verify the resource scheduler saw the same elastic config the runtime
    is using (reference elasticity/elasticity.py:206-237)."""
    if EC.DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            f"{EC.DEEPSPEED_ELASTICITY_CONFIG} env var not found; cannot "
            "guarantee the resource scheduler will scale this job with "
            "compatible chip counts.")
        return
    scheduler = ElasticityConfig(
        json.loads(os.environ[EC.DEEPSPEED_ELASTICITY_CONFIG]))
    runtime = ElasticityConfig(runtime_elastic_config_dict)
    for field in ("max_acceptable_batch_size", "micro_batches", "version"):
        if getattr(runtime, field) != getattr(scheduler, field):
            raise ElasticityConfigError(
                f"Elastic config '{field}={getattr(scheduler, field)}' seen "
                f"by the resource scheduler does not match the runtime value "
                f"{field}={getattr(runtime, field)}")


def compute_elastic_config(ds_config, target_deepspeed_version=__version__,
                           world_size=0):
    """Compute (final_batch_size, valid_chip_counts[, micro_batch]) from an
    elastic config — reference elasticity/elasticity.py:240.

    Deterministic for a given ``ds_config`` so both the scheduler and the
    runtime independently agree. With ``world_size`` > 0, also validates the
    world size and returns the largest compatible micro-batch size.
    """
    if not isinstance(ds_config, dict):
        raise ValueError(
            f"Expected ds_config dict, got {type(ds_config)}: {ds_config}")
    if EC.ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            f"'{EC.ELASTICITY}' is missing from the config; add it when "
            "running an elastic training job.")
    section = ds_config[EC.ELASTICITY]
    if not section.get(EC.ENABLED, EC.ENABLED_DEFAULT):
        raise ElasticityConfigError(
            "Elasticity is disabled; set 'enabled': true to run elastic.")

    elastic_config = ElasticityConfig(section)

    if float(elastic_config.version) > EC.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Elasticity version {elastic_config.version} requested but the "
            f"runtime supports up to {EC.LATEST_ELASTICITY_VERSION}")
    _compatible_version_check(target_deepspeed_version)

    if float(elastic_config.version) != 0.1:
        raise NotImplementedError(
            f"No elastic logic for version {elastic_config.version}")

    final_batch_size, valid_chips = _get_compatible_chips(
        micro_batches=elastic_config.micro_batches,
        max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
        min_chips=elastic_config.min_chips,
        max_chips=elastic_config.max_chips,
        prefer_larger=elastic_config.prefer_larger_batch_size)

    if world_size > 0:
        if world_size not in valid_chips:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not in the valid chip-count "
                f"list: {valid_chips}")
        micro_batch = next(
            (m for m in sorted(set(elastic_config.micro_batches), reverse=True)
             if (final_batch_size // world_size) % m == 0), None)
        assert micro_batch is not None, (
            f"No divisible micro batch for world_size={world_size}, "
            f"final_batch_size={final_batch_size}, "
            f"micro_batches={elastic_config.micro_batches}")
        return final_batch_size, valid_chips, micro_batch

    return final_batch_size, valid_chips
