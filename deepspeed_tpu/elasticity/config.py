"""Elasticity config schema — reference elasticity/config.py:30.

Keys are kept reference-compatible (``min_gpus``/``max_gpus``) and also
accepted in TPU spelling (``min_chips``/``max_chips``).
"""

import json

from deepspeed_tpu.elasticity import constants as EC


class ElasticityError(Exception):
    """Base exception for elasticity errors (reference elasticity/config.py:9)."""


class ElasticityConfigError(ElasticityError):
    """Malformed elasticity configuration (reference elasticity/config.py:16)."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """World size not in the valid chip-count list (reference
    elasticity/config.py:23)."""


class ElasticityConfig:
    """Typed view of the ``elasticity`` config section — reference
    elasticity/config.py:30.

    {
        "enabled": true,
        "max_train_batch_size": 2000,
        "micro_batch_sizes": [2, 4, 6],
        "min_chips": 1,
        "max_chips": 10000,
        "min_time": 20,
        "version": 0.1
    }
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(EC.ENABLED, EC.ENABLED_DEFAULT)
        if self.enabled:
            if EC.MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
                raise ElasticityConfigError(
                    f"Elasticity config missing {EC.MAX_ACCEPTABLE_BATCH_SIZE}")
            if EC.MICRO_BATCHES not in param_dict:
                raise ElasticityConfigError(
                    f"Elasticity config missing {EC.MICRO_BATCHES}")
        self.max_acceptable_batch_size = param_dict.get(
            EC.MAX_ACCEPTABLE_BATCH_SIZE, EC.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
        self.micro_batches = param_dict.get(EC.MICRO_BATCHES, EC.MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"Elasticity expected {EC.MICRO_BATCHES} to be a list of ints, "
                f"got {type(self.micro_batches)}: {self.micro_batches}")
        if not all(isinstance(m, int) and m > 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"Elasticity expected {EC.MICRO_BATCHES} to contain only "
                f"positive integers, got: {self.micro_batches}")

        self.min_chips = param_dict.get(
            EC.MIN_CHIPS, param_dict.get(EC.MIN_GPUS, EC.MIN_CHIPS_DEFAULT))
        self.max_chips = param_dict.get(
            EC.MAX_CHIPS, param_dict.get(EC.MAX_GPUS, EC.MAX_CHIPS_DEFAULT))
        if self.min_chips < 1 or self.max_chips < 1:
            raise ElasticityConfigError(
                f"Elasticity min/max chips must be > 0, given min: "
                f"{self.min_chips}, max: {self.max_chips}")
        if self.max_chips < self.min_chips:
            raise ElasticityConfigError(
                f"Elasticity min_chips cannot exceed max_chips, given min: "
                f"{self.min_chips}, max: {self.max_chips}")
        # reference-compatible aliases
        self.min_gpus = self.min_chips
        self.max_gpus = self.max_chips

        self.min_time = param_dict.get(EC.MIN_TIME, EC.MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(
                f"Elasticity min_time must be >= 0, given {self.min_time}")

        self.version = param_dict.get(EC.VERSION, EC.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            EC.PREFER_LARGER_BATCH, EC.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            EC.IGNORE_NON_ELASTIC_BATCH_INFO,
            EC.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
