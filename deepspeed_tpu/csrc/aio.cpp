// Async tensor I/O — TPU-host rebuild of the reference's libaio layer
// (csrc/aio/py_lib/deepspeed_py_aio_handle.cpp:14-33, thread pool
// deepspeed_aio_thread.cpp:84). Powers the NVMe tier of ZeRO-Offload/
// Infinity (swap_tensor/).
//
// Design: a handle owns `thread_count` worker threads and a submission
// queue. Reads/writes are split into `block_size` chunks executed with
// pread/pwrite (O_DIRECT when alignment allows), fanned across workers —
// the portable equivalent of the reference's io_submit queue-depth model.
// `wait()` blocks until all outstanding requests of the handle complete and
// returns the number completed.
//
// C ABI for ctypes: see deepspeed_tpu/ops/native/aio.py.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Request {
  int fd;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  bool write;
  // pieces of one user-submitted transfer share a countdown so `completed`
  // counts USER requests, not internal split chunks; `failed` is the
  // request-level flag so `errors` also counts USER requests (one failed
  // large transfer = one error, however many pieces it was split into)
  std::shared_ptr<std::atomic<int64_t>> remaining;
  std::shared_ptr<std::atomic<bool>> failed;
};

struct Handle {
  int64_t block_size;
  int queue_depth;
  int thread_count;
  bool single_submit;
  bool overlap_events;

  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  std::atomic<int64_t> inflight{0};
  std::atomic<int64_t> completed{0};
  std::atomic<int64_t> errors{0};
  bool stop = false;

  explicit Handle(int64_t bs, int qd, int tc, bool ss, bool oe)
      : block_size(bs), queue_depth(qd), thread_count(tc),
        single_submit(ss), overlap_events(oe) {
    for (int i = 0; i < thread_count; ++i) {
      workers.emplace_back([this] { this->run(); });
    }
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }

  void submit(Request r) {
    r.remaining = std::make_shared<std::atomic<int64_t>>(1);
    r.failed = std::make_shared<std::atomic<bool>>(false);
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(std::move(r));
      inflight.fetch_add(1);
    }
    cv_work.notify_one();
  }

  // Fan one large transfer across the worker pool (the reference slices a
  // tensor across its thread pool, deepspeed_aio_thread.cpp:84): split into
  // block_size pieces, capped at queue_depth*thread_count pieces so tiny
  // blocks don't drown the queue in bookkeeping.
  void submit_split(const Request& r) {
    const int64_t max_pieces =
        (int64_t)queue_depth * (thread_count > 0 ? thread_count : 1);
    int64_t pieces = (r.nbytes + block_size - 1) / block_size;
    if (pieces > max_pieces) pieces = max_pieces;
    if (pieces <= 1 || thread_count <= 1) {
      submit(r);
      return;
    }
    const int64_t piece = (r.nbytes + pieces - 1) / pieces;
    auto remaining = std::make_shared<std::atomic<int64_t>>(
        (r.nbytes + piece - 1) / piece);
    auto failed = std::make_shared<std::atomic<bool>>(false);
    {
      std::lock_guard<std::mutex> lk(mu);
      for (int64_t off = 0; off < r.nbytes; off += piece) {
        Request sub = r;
        sub.buf = static_cast<char*>(r.buf) + off;
        sub.offset = r.offset + off;
        sub.nbytes = std::min(piece, r.nbytes - off);
        sub.remaining = remaining;
        sub.failed = failed;
        queue.push_back(std::move(sub));
        inflight.fetch_add(1);
      }
    }
    cv_work.notify_all();
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        r = queue.front();
        queue.pop_front();
      }
      int64_t done = 0;
      char* p = static_cast<char*>(r.buf);
      bool failed = false;
      while (done < r.nbytes) {
        int64_t chunk = std::min(block_size, r.nbytes - done);
        ssize_t rc =
            r.write ? pwrite(r.fd, p + done, chunk, r.offset + done)
                    : pread(r.fd, p + done, chunk, r.offset + done);
        if (rc <= 0) {
          failed = true;
          break;
        }
        done += rc;
      }
      if (failed) r.failed->store(true);
      if (r.remaining->fetch_sub(1) == 1) {
        completed.fetch_add(1);
        if (r.failed->load()) errors.fetch_add(1);
      }
      // decrement+notify under mu: a waiter that checked the predicate but
      // has not yet blocked must not miss this wakeup
      {
        std::lock_guard<std::mutex> lk(mu);
        if (inflight.fetch_sub(1) == 1) cv_done.notify_all();
      }
    }
  }

  int64_t wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] { return inflight.load() == 0; });
    return completed.exchange(0);
  }
};

}  // namespace

extern "C" {

void* aio_handle_create(int64_t block_size, int queue_depth, int thread_count,
                        int single_submit, int overlap_events) {
  return new Handle(block_size, queue_depth, thread_count,
                    single_submit != 0, overlap_events != 0);
}

void aio_handle_destroy(void* h) { delete static_cast<Handle*>(h); }

int aio_open(const char* path, int for_write) {
  int flags = for_write ? (O_WRONLY | O_CREAT | O_TRUNC) : O_RDONLY;
  return open(path, flags, 0644);
}

void aio_close(int fd) { close(fd); }

// async: enqueue and return immediately; pair with aio_handle_wait.
// Large transfers split across the worker pool.
void aio_pread(void* h, int fd, void* buf, int64_t nbytes, int64_t offset) {
  static_cast<Handle*>(h)->submit_split({fd, buf, nbytes, offset, false});
}

void aio_pwrite(void* h, int fd, void* buf, int64_t nbytes, int64_t offset) {
  static_cast<Handle*>(h)->submit_split({fd, buf, nbytes, offset, true});
}

int64_t aio_handle_wait(void* h) { return static_cast<Handle*>(h)->wait(); }

// returns and clears the error count, so one failed batch does not poison
// later batches on the same handle
int64_t aio_handle_errors(void* h) {
  return static_cast<Handle*>(h)->errors.exchange(0);
}

// sync convenience: whole-tensor read/write through the pool
int64_t aio_sync_pread(void* h, int fd, void* buf, int64_t nbytes,
                       int64_t offset) {
  auto* handle = static_cast<Handle*>(h);
  handle->submit_split({fd, buf, nbytes, offset, false});
  return handle->wait();
}

int64_t aio_sync_pwrite(void* h, int fd, void* buf, int64_t nbytes,
                        int64_t offset) {
  auto* handle = static_cast<Handle*>(h);
  handle->submit_split({fd, buf, nbytes, offset, true});
  return handle->wait();
}

}  // extern "C"
