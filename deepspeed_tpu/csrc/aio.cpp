// Async tensor I/O — TPU-host rebuild of the reference's libaio layer
// (csrc/aio/py_lib/deepspeed_py_aio_handle.cpp:14-33, thread pool
// deepspeed_aio_thread.cpp:84, io_submit driver
// csrc/aio/common/deepspeed_aio_common.cpp). Powers the NVMe tier of
// ZeRO-Offload/Infinity (swap_tensor/).
//
// Two backends behind one handle:
//
// - **io_uring** (default when the kernel supports it): a raw-syscall
//   submission/completion ring (no liburing dependency) with
//   `queue_depth` requests in flight — the modern kernel-async successor
//   of the reference's libaio io_submit path. One ring thread fills SQEs
//   from the handle queue and reaps CQEs, resubmitting short transfers.
// - **thread pool** (fallback; `backend=threads`): `thread_count` workers
//   executing pread/pwrite pieces — portable to kernels/seccomp profiles
//   without io_uring.
//
// Either way, reads/writes are split into `block_size` pieces fanned across
// the queue, and `wait()` blocks until all outstanding requests of the
// handle complete, returning the number completed.
//
// C ABI for ctypes: see deepspeed_tpu/ops/native/aio.py.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

struct Request {
  int fd;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  bool write;
  // pieces of one user-submitted transfer share a countdown so `completed`
  // counts USER requests, not internal split chunks; `failed` is the
  // request-level flag so `errors` also counts USER requests (one failed
  // large transfer = one error, however many pieces it was split into)
  std::shared_ptr<std::atomic<int64_t>> remaining;
  std::shared_ptr<std::atomic<bool>> failed;
};

// ---------------------------------------------------------------- io_uring
// Minimal raw-syscall ring (the image has no liburing). Memory ordering on
// the shared head/tail indices follows the io_uring contract: acquire-load
// the index the kernel writes, release-store the index we write.

static int sys_io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}

static int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                              unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
                      nullptr, 0);
}

static int sys_io_uring_register(int fd, unsigned opcode, void* arg,
                                 unsigned nr_args) {
  return (int)syscall(__NR_io_uring_register, fd, opcode, arg, nr_args);
}

struct IoUring {
  int ring_fd = -1;
  unsigned entries = 0;
  unsigned cq_entries_n = 0;  // in-flight bound: completions must fit the CQ

  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_sqe* sqes = nullptr;
  io_uring_cqe* cqes = nullptr;

  void* sq_ptr = nullptr;
  size_t sq_len = 0;
  void* cq_ptr = nullptr;
  size_t cq_len = 0;
  size_t sqes_len = 0;

  bool init(unsigned want_entries) {
    struct io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    if (want_entries < 1) want_entries = 1;
    ring_fd = sys_io_uring_setup(want_entries, &p);
    if (ring_fd < 0) return false;
    entries = p.sq_entries;
    cq_entries_n = p.cq_entries;

    // IORING_OP_READ/WRITE need kernel >= 5.6; probe (same vintage) instead
    // of discovering via -EINVAL completions at training time — a 5.1-5.5
    // kernel passes setup but must fall back to the thread pool
    {
      // io_uring_probe ends in a flexible array member: allocate raw bytes
      alignas(io_uring_probe) char buf[sizeof(io_uring_probe) +
                                       64 * sizeof(io_uring_probe_op)];
      std::memset(buf, 0, sizeof(buf));
      auto* probe = reinterpret_cast<io_uring_probe*>(buf);
      if (sys_io_uring_register(ring_fd, IORING_REGISTER_PROBE, probe, 64) < 0
          || probe->last_op < IORING_OP_WRITE
          || !(probe->ops[IORING_OP_READ].flags & IO_URING_OP_SUPPORTED)
          || !(probe->ops[IORING_OP_WRITE].flags & IO_URING_OP_SUPPORTED)) {
        destroy();
        return false;
      }
    }

    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    if (p.features & IORING_FEAT_SINGLE_MMAP) {
      sq_len = cq_len = std::max(sq_len, cq_len);
    }
    sq_ptr = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQ_RING);
    if (sq_ptr == MAP_FAILED) { destroy(); return false; }
    if (p.features & IORING_FEAT_SINGLE_MMAP) {
      cq_ptr = sq_ptr;
    } else {
      cq_ptr = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_CQ_RING);
      if (cq_ptr == MAP_FAILED) { cq_ptr = nullptr; destroy(); return false; }
    }
    sqes_len = p.sq_entries * sizeof(io_uring_sqe);
    sqes = static_cast<io_uring_sqe*>(
        mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
             MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQES));
    if (sqes == MAP_FAILED) { sqes = nullptr; destroy(); return false; }

    auto base = static_cast<char*>(sq_ptr);
    sq_head = reinterpret_cast<unsigned*>(base + p.sq_off.head);
    sq_tail = reinterpret_cast<unsigned*>(base + p.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(base + p.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(base + p.sq_off.array);
    auto cbase = static_cast<char*>(cq_ptr);
    cq_head = reinterpret_cast<unsigned*>(cbase + p.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cbase + p.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cbase + p.cq_off.ring_mask);
    cqes = reinterpret_cast<io_uring_cqe*>(cbase + p.cq_off.cqes);
    return true;
  }

  void destroy() {
    if (sqes) munmap(sqes, sqes_len);
    if (cq_ptr && cq_ptr != sq_ptr) munmap(cq_ptr, cq_len);
    if (sq_ptr) munmap(sq_ptr, sq_len);
    sqes = nullptr;
    sq_ptr = cq_ptr = nullptr;
    if (ring_fd >= 0) close(ring_fd);
    ring_fd = -1;
  }

  // space for one more SQE? (single producer: this thread)
  bool sq_full() const {
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    return (*sq_tail - head) >= entries;
  }

  void push(const Request* piece) {
    unsigned tail = *sq_tail;
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* sqe = &sqes[idx];
    std::memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = piece->write ? IORING_OP_WRITE : IORING_OP_READ;
    sqe->fd = piece->fd;
    sqe->addr = (uint64_t)(uintptr_t)piece->buf;
    sqe->len = (unsigned)piece->nbytes;
    sqe->off = (uint64_t)piece->offset;
    sqe->user_data = (uint64_t)(uintptr_t)piece;
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
  }
};

struct Handle {
  int64_t block_size;
  int queue_depth;
  int thread_count;
  bool single_submit;
  bool overlap_events;

  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  std::atomic<int64_t> inflight{0};
  std::atomic<int64_t> completed{0};
  std::atomic<int64_t> errors{0};
  bool stop = false;

  IoUring uring;
  bool use_uring = false;

  // backend: 0 = auto (io_uring if the kernel allows, else threads),
  //          1 = threads, 2 = io_uring (required)
  explicit Handle(int64_t bs, int qd, int tc, bool ss, bool oe,
                  int backend = 0)
      : block_size(bs), queue_depth(qd), thread_count(tc),
        single_submit(ss), overlap_events(oe) {
    if (backend != 1) {
      use_uring = uring.init((unsigned)(qd > 0 ? qd : 8));
      if (!use_uring && backend == 2) return;  // caller checks aio_handle_ok
    }
    if (use_uring) {
      workers.emplace_back([this] { this->run_uring(); });
    } else {
      for (int i = 0; i < thread_count; ++i) {
        workers.emplace_back([this] { this->run(); });
      }
    }
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
    if (use_uring) uring.destroy();
  }

  void submit(Request r) {
    r.remaining = std::make_shared<std::atomic<int64_t>>(1);
    r.failed = std::make_shared<std::atomic<bool>>(false);
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(std::move(r));
      inflight.fetch_add(1);
    }
    cv_work.notify_one();
  }

  // Fan one large transfer across the backend's parallelism (the reference
  // slices a tensor across its thread pool, deepspeed_aio_thread.cpp:84):
  // split into block_size pieces, capped so tiny blocks don't drown the
  // queue in bookkeeping. The ring overlaps queue_depth SQEs regardless of
  // thread_count (one ring thread only does bookkeeping); the pool
  // overlaps thread_count workers.
  void submit_split(const Request& r) {
    const int64_t lanes = use_uring
        ? (int64_t)(queue_depth > 0 ? queue_depth : 1)
        : (int64_t)(thread_count > 0 ? thread_count : 1);
    const int64_t max_pieces = std::max(
        (int64_t)queue_depth * (thread_count > 0 ? thread_count : 1), lanes);
    int64_t pieces = (r.nbytes + block_size - 1) / block_size;
    if (pieces > max_pieces) pieces = max_pieces;
    if (lanes <= 1) pieces = 1;
    if (use_uring) {
      // an SQE's len field is u32: every piece must stay below 4 GiB
      // (the thread pool loops block_size pread/pwrites internally and has
      // no such bound)
      const int64_t kMaxPiece = (int64_t)1 << 30;
      const int64_t min_pieces = (r.nbytes + kMaxPiece - 1) / kMaxPiece;
      if (pieces < min_pieces) pieces = min_pieces;
    }
    if (pieces <= 1) {
      submit(r);
      return;
    }
    const int64_t piece = (r.nbytes + pieces - 1) / pieces;
    auto remaining = std::make_shared<std::atomic<int64_t>>(
        (r.nbytes + piece - 1) / piece);
    auto failed = std::make_shared<std::atomic<bool>>(false);
    {
      std::lock_guard<std::mutex> lk(mu);
      for (int64_t off = 0; off < r.nbytes; off += piece) {
        Request sub = r;
        sub.buf = static_cast<char*>(r.buf) + off;
        sub.offset = r.offset + off;
        sub.nbytes = std::min(piece, r.nbytes - off);
        sub.remaining = remaining;
        sub.failed = failed;
        queue.push_back(std::move(sub));
        inflight.fetch_add(1);
      }
    }
    cv_work.notify_all();
  }

  // piece fully done (ok or failed): resolve user-request accounting
  void finish_piece(const Request& r, bool ok) {
    if (!ok) r.failed->store(true);
    if (r.remaining->fetch_sub(1) == 1) {
      completed.fetch_add(1);
      if (r.failed->load()) errors.fetch_add(1);
    }
    // decrement+notify under mu: a waiter that checked the predicate but
    // has not yet blocked must not miss this wakeup
    {
      std::lock_guard<std::mutex> lk(mu);
      if (inflight.fetch_sub(1) == 1) cv_done.notify_all();
    }
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        r = queue.front();
        queue.pop_front();
      }
      int64_t done = 0;
      char* p = static_cast<char*>(r.buf);
      bool failed = false;
      while (done < r.nbytes) {
        int64_t chunk = std::min(block_size, r.nbytes - done);
        ssize_t rc =
            r.write ? pwrite(r.fd, p + done, chunk, r.offset + done)
                    : pread(r.fd, p + done, chunk, r.offset + done);
        if (rc <= 0) {
          failed = true;
          break;
        }
        done += rc;
      }
      finish_piece(r, !failed);
    }
  }

  // Single ring thread: fill SQEs from the queue up to queue_depth in
  // flight, io_uring_enter to submit + wait, reap CQEs, resubmit short
  // transfers. The kernel does the parallel I/O — this thread only does
  // bookkeeping (the reference needed a whole thread pool for the same
  // concurrency; the ring replaces it).
  void run_uring() {
    size_t ring_inflight = 0;   // submitted (or pushed), not yet completed
    unsigned unsubmitted = 0;   // SQEs pushed but not yet consumed by enter
    for (;;) {
      if (ring_inflight == 0) {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        // bound in-flight to the CQ so completions can never overflow it
        // (overflow makes enter return -EBUSY and strands pushed SQEs)
        while (!queue.empty() && !uring.sq_full()
               && ring_inflight < uring.cq_entries_n) {
          // heap copy: the SQE's user_data must outlive this scope
          Request* piece = new Request(queue.front());
          queue.pop_front();
          uring.push(piece);
          ++ring_inflight;
          ++unsubmitted;
        }
      }
      if (ring_inflight == 0) continue;
      int consumed = sys_io_uring_enter(
          uring.ring_fd, unsubmitted,
          ring_inflight > unsubmitted ? 1 : 0, IORING_ENTER_GETEVENTS);
      // partial consumption (or -EBUSY/-EINTR) leaves a remainder that the
      // next enter must count again — losing it would deadlock wait()
      if (consumed > 0) unsubmitted -= (unsigned)consumed;
      unsigned head = __atomic_load_n(uring.cq_head, __ATOMIC_ACQUIRE);
      unsigned tail = __atomic_load_n(uring.cq_tail, __ATOMIC_ACQUIRE);
      while (head != tail) {
        io_uring_cqe* cqe = &uring.cqes[head & *uring.cq_mask];
        Request* piece =
            reinterpret_cast<Request*>((uintptr_t)cqe->user_data);
        int32_t res = cqe->res;
        ++head;
        --ring_inflight;
        if (res > 0 && (int64_t)res < piece->nbytes) {
          // short transfer: requeue the remainder (keeps user accounting
          // open — finish_piece only fires when the piece is whole)
          piece->buf = static_cast<char*>(piece->buf) + res;
          piece->offset += res;
          piece->nbytes -= res;
          std::lock_guard<std::mutex> lk(mu);
          queue.push_front(*piece);
        } else {
          finish_piece(*piece, res > 0 || piece->nbytes == 0);
        }
        delete piece;
      }
      __atomic_store_n(uring.cq_head, head, __ATOMIC_RELEASE);
    }
  }

  int64_t wait() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] { return inflight.load() == 0; });
    return completed.exchange(0);
  }
};

}  // namespace

extern "C" {

void* aio_handle_create(int64_t block_size, int queue_depth, int thread_count,
                        int single_submit, int overlap_events) {
  return new Handle(block_size, queue_depth, thread_count,
                    single_submit != 0, overlap_events != 0);
}

// backend: 0 = auto, 1 = thread pool, 2 = io_uring (NULL if unsupported)
void* aio_handle_create2(int64_t block_size, int queue_depth, int thread_count,
                         int single_submit, int overlap_events, int backend) {
  auto* h = new Handle(block_size, queue_depth, thread_count,
                       single_submit != 0, overlap_events != 0, backend);
  if (backend == 2 && !h->use_uring) {
    delete h;
    return nullptr;
  }
  return h;
}

// 1 = io_uring, 0 = thread pool
int aio_handle_backend(void* h) {
  return static_cast<Handle*>(h)->use_uring ? 1 : 0;
}

void aio_handle_destroy(void* h) { delete static_cast<Handle*>(h); }

int aio_open(const char* path, int for_write) {
  int flags = for_write ? (O_WRONLY | O_CREAT | O_TRUNC) : O_RDONLY;
  return open(path, flags, 0644);
}

void aio_close(int fd) { close(fd); }

// async: enqueue and return immediately; pair with aio_handle_wait.
// Large transfers split across the worker pool.
void aio_pread(void* h, int fd, void* buf, int64_t nbytes, int64_t offset) {
  static_cast<Handle*>(h)->submit_split({fd, buf, nbytes, offset, false});
}

void aio_pwrite(void* h, int fd, void* buf, int64_t nbytes, int64_t offset) {
  static_cast<Handle*>(h)->submit_split({fd, buf, nbytes, offset, true});
}

int64_t aio_handle_wait(void* h) { return static_cast<Handle*>(h)->wait(); }

// returns and clears the error count, so one failed batch does not poison
// later batches on the same handle
int64_t aio_handle_errors(void* h) {
  return static_cast<Handle*>(h)->errors.exchange(0);
}

// sync convenience: whole-tensor read/write through the pool
int64_t aio_sync_pread(void* h, int fd, void* buf, int64_t nbytes,
                       int64_t offset) {
  auto* handle = static_cast<Handle*>(h);
  handle->submit_split({fd, buf, nbytes, offset, false});
  return handle->wait();
}

int64_t aio_sync_pwrite(void* h, int fd, void* buf, int64_t nbytes,
                        int64_t offset) {
  auto* handle = static_cast<Handle*>(h);
  handle->submit_split({fd, buf, nbytes, offset, true});
  return handle->wait();
}

}  // extern "C"
