// SIMD CPU Adam — TPU-host rebuild of the reference's AVX Adam
// (csrc/adam/cpu_adam.cpp:21, SIMD macros csrc/includes/cpu_adam.h:25-41).
//
// Runs the ZeRO-Offload optimizer step on the TPU-VM host over fp32 numpy
// views. Auto-vectorized hot loop (-O3 -march=native turns it into
// AVX2/AVX-512 or NEON depending on the host) + OpenMP across chunks —
// same design point as the reference, without hand-written intrinsics so
// one source serves x86 and aarch64 TPU-VM hosts.
//
// C ABI for ctypes: see deepspeed_tpu/ops/native/cpu_adam.py.

#include <cmath>
#include <cstdint>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// One fused Adam/AdamW step over a flat fp32 tensor, in place.
void ds_adam_step(float* params,
                  const float* grads,
                  float* exp_avg,
                  float* exp_avg_sq,
                  int64_t n,
                  int64_t step,
                  float lr,
                  float beta1,
                  float beta2,
                  float eps,
                  float weight_decay,
                  int adamw_mode,
                  int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
    float m = beta1 * exp_avg[i] + omb1 * g;
    float v = beta2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float update = (m * inv_bc1) / denom;
    if (weight_decay != 0.0f && adamw_mode) update += weight_decay * p;
    params[i] = p - lr * update;
  }
}

// Same step but also writes a bf16 copy of the updated params (the tile the
// reference copies back to GPU overlapped with compute, cpu_adam.cpp:67).
void ds_adam_step_plus_copy(float* params,
                            const float* grads,
                            float* exp_avg,
                            float* exp_avg_sq,
                            uint16_t* params_bf16,
                            int64_t n,
                            int64_t step,
                            float lr,
                            float beta1,
                            float beta2,
                            float eps,
                            float weight_decay,
                            int adamw_mode,
                            int bias_correction) {
  ds_adam_step(params, grads, exp_avg, exp_avg_sq, n, step, lr, beta1, beta2,
               eps, weight_decay, adamw_mode, bias_correction);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    // round-to-nearest-even fp32→bf16
    uint32_t bits;
    __builtin_memcpy(&bits, &params[i], 4);
    uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
    params_bf16[i] = (uint16_t)((bits + rounding) >> 16);
  }
}

int ds_adam_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
