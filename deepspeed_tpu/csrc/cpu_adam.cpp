// SIMD CPU Adam — TPU-host rebuild of the reference's AVX Adam
// (csrc/adam/cpu_adam.cpp:21, SIMD macros csrc/includes/cpu_adam.h:25-41).
//
// Runs the ZeRO-Offload optimizer step on the TPU-VM host over fp32 numpy
// views. Auto-vectorized hot loop (-O3 -march=native turns it into
// AVX2/AVX-512 or NEON depending on the host) + OpenMP across chunks —
// same design point as the reference, without hand-written intrinsics so
// one source serves x86 and aarch64 TPU-VM hosts.
//
// C ABI for ctypes: see deepspeed_tpu/ops/native/cpu_adam.py.

#include <cmath>
#include <cstdint>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// One fused Adam/AdamW step over a flat fp32 tensor, in place.
void ds_adam_step(float* params,
                  const float* grads,
                  float* exp_avg,
                  float* exp_avg_sq,
                  int64_t n,
                  int64_t step,
                  float lr,
                  float beta1,
                  float beta2,
                  float eps,
                  float weight_decay,
                  int adamw_mode,
                  int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
    float m = beta1 * exp_avg[i] + omb1 * g;
    float v = beta2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float update = (m * inv_bc1) / denom;
    if (weight_decay != 0.0f && adamw_mode) update += weight_decay * p;
    params[i] = p - lr * update;
  }
}

// Round-to-nearest-even fp32→bf16 with a NaN guard: the rounding add would
// otherwise carry a high-mantissa NaN through the exponent into ±0/Inf —
// and NaNs (fp16-overflow markers) are exactly what the offload staging
// must preserve for the skip-step logic.
static inline uint16_t fp32_bits_to_bf16(uint32_t bits) {
  if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu)) {
    return (uint16_t)(((bits >> 16) & 0x8000u) | 0x7FC0u);
  }
  uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return (uint16_t)((bits + rounding) >> 16);
}

// Same step but also writes a bf16 copy of the updated params (the tile the
// reference copies back to GPU overlapped with compute, cpu_adam.cpp:67).
void ds_adam_step_plus_copy(float* params,
                            const float* grads,
                            float* exp_avg,
                            float* exp_avg_sq,
                            uint16_t* params_bf16,
                            int64_t n,
                            int64_t step,
                            float lr,
                            float beta1,
                            float beta2,
                            float eps,
                            float weight_decay,
                            int adamw_mode,
                            int bias_correction) {
  ds_adam_step(params, grads, exp_avg, exp_avg_sq, n, step, lr, beta1, beta2,
               eps, weight_decay, adamw_mode, bias_correction);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    __builtin_memcpy(&bits, &params[i], 4);
    params_bf16[i] = fp32_bits_to_bf16(bits);
  }
}

// Extended single-pass step for the pipelined offload tier
// (runtime/zero/offload.py step_streamed): reads grads directly in their
// wire dtype (bf16 halves the d2h bytes) with the unscale/clip coefficient
// folded into the read, updates master fp32 params + moments, and emits
// the bf16 copy the engine pushes back to the device — one memory pass
// where the unextended path needed three (widen, scale, step) plus a
// separate conversion pass. The reference overlaps the same stages with
// CUDA streams (csrc/adam/cpu_adam.cpp:67-120).
void ds_adam_step_ex(float* params,
                     const void* grads,
                     int grads_bf16,      // 1: grads are bf16 (uint16 bits)
                     float grad_scale,    // multiplied into every grad read
                     float* exp_avg,
                     float* exp_avg_sq,
                     uint16_t* params_bf16_out,  // nullable
                     int64_t n,
                     int64_t step,
                     float lr,
                     float beta1,
                     float beta2,
                     float eps,
                     float weight_decay,
                     int adamw_mode,
                     int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
  const float* gf = static_cast<const float*>(grads);
  const uint16_t* gh = static_cast<const uint16_t*>(grads);

#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float g;
    if (grads_bf16) {
      uint32_t bits = ((uint32_t)gh[i]) << 16;
      __builtin_memcpy(&g, &bits, 4);
    } else {
      g = gf[i];
    }
    g *= grad_scale;
    float p = params[i];
    if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
    float m = beta1 * exp_avg[i] + omb1 * g;
    float v = beta2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float update = (m * inv_bc1) / denom;
    if (weight_decay != 0.0f && adamw_mode) update += weight_decay * p;
    p -= lr * update;
    params[i] = p;
    if (params_bf16_out) {
      uint32_t bits;
      __builtin_memcpy(&bits, &p, 4);
      params_bf16_out[i] = fp32_bits_to_bf16(bits);
    }
  }
}

// LAMB twin of ds_adam_step_ex (trust-ratio semantics of ds_lamb_step).
void ds_lamb_step_ex(float* params,
                     const void* grads,
                     int grads_bf16,
                     float grad_scale,
                     float* exp_avg,
                     float* exp_avg_sq,
                     float* update_buf,   // scratch, n floats
                     uint16_t* params_bf16_out,  // nullable
                     int64_t n,
                     int64_t step,
                     float lr,
                     float beta1,
                     float beta2,
                     float eps,
                     float weight_decay,
                     float max_coeff,
                     float min_coeff,
                     int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);
  const float* gf = static_cast<const float*>(grads);
  const uint16_t* gh = static_cast<const uint16_t*>(grads);

  double p_sq = 0.0, u_sq = 0.0;
#pragma omp parallel for schedule(static) reduction(+ : p_sq, u_sq)
  for (int64_t i = 0; i < n; ++i) {
    float g;
    if (grads_bf16) {
      uint32_t bits = ((uint32_t)gh[i]) << 16;
      __builtin_memcpy(&g, &bits, 4);
    } else {
      g = gf[i];
    }
    g *= grad_scale;
    float p = params[i];
    float m = beta1 * exp_avg[i] + omb1 * g;
    float v = beta2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float u = (m * inv_bc1) / denom;
    if (weight_decay != 0.0f) u += weight_decay * p;
    update_buf[i] = u;
    p_sq += (double)p * p;
    u_sq += (double)u * u;
  }
  float trust = 1.0f;
  if (p_sq > 0.0 && u_sq > 0.0) {
    trust = (float)(std::sqrt(p_sq) / std::sqrt(u_sq));
    if (trust > max_coeff) trust = max_coeff;
    if (trust < min_coeff) trust = min_coeff;
  }
  const float step_size = lr * trust;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float p = params[i] - step_size * update_buf[i];
    params[i] = p;
    if (params_bf16_out) {
      uint32_t bits;
      __builtin_memcpy(&bits, &p, 4);
      params_bf16_out[i] = fp32_bits_to_bf16(bits);
    }
  }
}

// Multi-tensor apply (reference csrc/adam/multi_tensor_adam.cu:163 /
// multi_tensor_apply.cuh): one call steps a whole parameter list. The
// OpenMP region spans all tensors so small leaves don't serialize on
// per-call fork/join.
void ds_adam_step_multi(float** params,
                        const float** grads,
                        float** exp_avg,
                        float** exp_avg_sq,
                        const int64_t* sizes,
                        int64_t n_tensors,
                        int64_t step,
                        float lr,
                        float beta1,
                        float beta2,
                        float eps,
                        float weight_decay,
                        int adamw_mode,
                        int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);

#pragma omp parallel
  for (int64_t t = 0; t < n_tensors; ++t) {
    float* p_ = params[t];
    const float* g_ = grads[t];
    float* m_ = exp_avg[t];
    float* v_ = exp_avg_sq[t];
    const int64_t n = sizes[t];
#pragma omp for schedule(static) nowait
    for (int64_t i = 0; i < n; ++i) {
      float g = g_[i];
      float p = p_[i];
      if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
      float m = beta1 * m_[i] + omb1 * g;
      float v = beta2 * v_[i] + omb2 * g * g;
      m_[i] = m;
      v_[i] = v;
      float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
      float update = (m * inv_bc1) / denom;
      if (weight_decay != 0.0f && adamw_mode) update += weight_decay * p;
      p_[i] = p - lr * update;
    }
  }
}

// Host LAMB step over one flat tensor (reference
// csrc/lamb/fused_lamb_cuda_kernel.cu:469): Adam-style update, then a
// per-tensor trust ratio ||p|| / ||update|| clamped to
// [min_coeff, max_coeff]. Two-pass: the norms need the full update before
// any element of p moves.
void ds_lamb_step(float* params,
                  const float* grads,
                  float* exp_avg,
                  float* exp_avg_sq,
                  float* update_buf,   // scratch, n floats
                  int64_t n,
                  int64_t step,
                  float lr,
                  float beta1,
                  float beta2,
                  float eps,
                  float weight_decay,
                  float max_coeff,
                  float min_coeff,
                  int bias_correction) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float omb1 = 1.0f - beta1;
  const float omb2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2_sqrt = 1.0f / std::sqrt(bc2);

  double p_sq = 0.0, u_sq = 0.0;
#pragma omp parallel for schedule(static) reduction(+ : p_sq, u_sq)
  for (int64_t i = 0; i < n; ++i) {
    float g = grads[i];
    float p = params[i];
    float m = beta1 * exp_avg[i] + omb1 * g;
    float v = beta2 * exp_avg_sq[i] + omb2 * g * g;
    exp_avg[i] = m;
    exp_avg_sq[i] = v;
    float denom = std::sqrt(v) * inv_bc2_sqrt + eps;
    float u = (m * inv_bc1) / denom;
    if (weight_decay != 0.0f) u += weight_decay * p;
    update_buf[i] = u;
    p_sq += (double)p * p;
    u_sq += (double)u * u;
  }
  float trust = 1.0f;
  if (p_sq > 0.0 && u_sq > 0.0) {
    trust = (float)(std::sqrt(p_sq) / std::sqrt(u_sq));
    if (trust > max_coeff) trust = max_coeff;
    if (trust < min_coeff) trust = min_coeff;
  }
  const float step_size = lr * trust;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    params[i] -= step_size * update_buf[i];
  }
}

// Staging conversions for the offload tiers (the reference's overlapped
// fp16 copy tiles, cpu_adam.cpp:67): round-to-nearest-even fp32→bf16 and
// the exact widening bf16→fp32.
void ds_fp32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    __builtin_memcpy(&bits, &src[i], 4);
    dst[i] = fp32_bits_to_bf16(bits);
  }
}

void ds_bf16_to_fp32(const uint16_t* src, float* dst, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits = ((uint32_t)src[i]) << 16;
    __builtin_memcpy(&dst[i], &bits, 4);
  }
}

// L2 norm over a flat tensor (fp64 accumulation) — host-side grad-norm for
// the offload clip path.
double ds_l2_norm_sq(const float* x, int64_t n) {
  double acc = 0.0;
#pragma omp parallel for schedule(static) reduction(+ : acc)
  for (int64_t i = 0; i < n; ++i) acc += (double)x[i] * x[i];
  return acc;
}

int ds_adam_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
