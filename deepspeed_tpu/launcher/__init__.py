from deepspeed_tpu.launcher.runner import main as runner_main  # noqa: F401
