"""Launcher constants — rebuild of deepspeed/launcher/constants.py.

On TPU pods the transport between hosts for *launching* is still ssh (or an
MPI runner); the training-time transport is ICI/DCN managed by the JAX
runtime, so there is no NCCL_* env surface — the propagated prefixes are the
JAX/libtpu ones instead (reference launcher/runner.py:27 EXPORT_ENVS).
"""

SSH_LAUNCHER = "ssh"
PDSH_LAUNCHER = "pdsh"
OPENMPI_LAUNCHER = "openmpi"

PDSH_MAX_FAN_OUT = 1024

DEFAULT_COORDINATOR_PORT = 8476

# Env-var prefixes forwarded from the operator's shell to every worker
# (reference EXPORT_ENVS = NCCL/PYTHON/MV2/UCX → TPU equivalents).
EXPORT_ENV_PREFIXES = ["JAX", "XLA", "LIBTPU", "TPU", "PYTHON", "DSTPU"]

# Optional per-job env file, one KEY=VALUE per line, shipped to all workers
# (reference DEEPSPEED_ENVIRONMENT_NAME ".deepspeed_env").
ENVIRONMENT_FILE_NAME = ".dstpu_env"
