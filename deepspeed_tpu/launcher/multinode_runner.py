"""Multi-node launch backends — rebuild of deepspeed/launcher/multinode_runner.py.

Each runner turns (world_info, per-host launch module, exports) into one
command the front-end execs. The reference ships pdsh/mpirun/mvapich; TPU
pods are plain ssh-reachable VMs so the default here is a portable ssh
fan-out, with pdsh and OpenMPI kept for parity on clusters that have them.
"""

import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod

from deepspeed_tpu.launcher.constants import PDSH_MAX_FAN_OUT
from deepspeed_tpu.utils.logging import logger


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    def add_export(self, key, var):
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args

    def _launch_cmd(self, node_rank_token):
        """The per-host `python -m deepspeed_tpu.launcher.launch …` tail."""
        return [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            f"--node_rank={node_rank_token}",
            f"--coordinator_addr={self.args.coordinator_addr}",
            f"--coordinator_port={self.args.coordinator_port}",
        ]


class SSHRunner(MultiNodeRunner):
    """Portable fan-out: one `ssh host 'exports; cd; launch …'` per host,
    wrapped in a single local shell that waits on all of them and returns
    the first non-zero status."""

    def backend_exists(self):
        return shutil.which("ssh")

    def get_cmd(self, environment, active_resources):
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        workdir = os.path.abspath(".")
        ssh_opts = self.args.launcher_args or ""
        per_host = []
        for rank, host in enumerate(active_resources):
            tail = " ".join(
                self._launch_cmd(rank) + [self.user_script]
                + list(self.user_arguments))
            remote = shlex.quote(f"{exports}cd {workdir}; {tail}")
            per_host.append(
                f"ssh -o StrictHostKeyChecking=no {ssh_opts} {host} "
                f"{remote} &")
        # no `set -m`: the backgrounded ssh children must stay in the
        # front-end's process group so Ctrl-C/SIGTERM reaches them (job
        # control would re-parent them into their own groups and orphan
        # the remote jobs)
        script = ("pids=(); "
                  + " ".join(f"{c} pids+=($!);" for c in per_host)
                  + " rc=0; for p in ${pids[@]}; do wait $p || rc=$?; done; "
                  "exit $rc")
        logger.info("Running on: %s", ",".join(active_resources))
        return ["bash", "-c", script]


class PDSHRunner(MultiNodeRunner):
    def backend_exists(self):
        return shutil.which("pdsh")

    def parse_user_args(self):
        # quote non-flag args so pdsh's remote shell keeps them whole
        return [x if x.startswith("-") else f"'{x}'"
                for x in self.args.user_args]

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        logger.info("Running on: %s", active_workers)
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        extra = self.args.launcher_args.split() if \
            self.args.launcher_args else []
        # %n is pdsh's per-host index → node_rank
        return (["pdsh", "-f", str(PDSH_MAX_FAN_OUT)] + extra
                + ["-w", active_workers,
                   exports, f"cd {os.path.abspath('.')};"]
                + self._launch_cmd("%n")
                + [self.user_script] + self.user_arguments)


class OpenMPIRunner(MultiNodeRunner):
    """mpirun with one rank per host; each rank discovers its node_rank from
    OMPI env vars, so the launch module is invoked with --node_rank=ompi."""

    def backend_exists(self):
        return shutil.which("ompi_info")

    def get_cmd(self, environment, active_resources):
        total_hosts = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)
        export_args = []
        for k, v in self.exports.items():
            export_args += ["-x", f"{k}={v}"]
        extra = self.args.launcher_args.split() if \
            self.args.launcher_args else []
        return (["mpirun", "-n", str(total_hosts), "--host", hosts,
                 "--mca", "btl_tcp_if_include", "eth0"]
                + export_args + extra
                + self._launch_cmd("ompi")
                + [self.user_script] + list(self.user_arguments))
