"""Per-host launcher — rebuild of deepspeed/launcher/launch.py.

The reference spawns one process per GPU with RANK/LOCAL_RANK/WORLD_SIZE.
The JAX process model is one process per host owning every local chip, so
here each host runs ONE worker whose environment carries the coordinator
address and its process id; `deepspeed_tpu.init_distributed` (utils/
distributed.py) picks these up and calls `jax.distributed.initialize`.

Kept from the reference: base64 world-info decoding, SIGINT/SIGTERM
propagation to children, non-zero-exit fail-fast monitoring
(launch.py:128-168).

``--supervise`` (ISSUE 15) upgrades fail-fast into self-healing for
single-node worlds: the worker runs under the fault-tolerance
supervisor (runtime/elastic/supervisor.py) — child liveness +
heartbeat monitoring, bounded jittered-backoff restarts from the
latest valid snapshot, one latched ``crash_loop`` dump when the budget
is spent. Multi-node worlds keep fail-fast here: a per-host launcher
cannot re-rendezvous a world whose other hosts it does not own — run
the supervisor CLI (``python -m deepspeed_tpu.runtime.elastic.
supervisor``) on the coordinator host for the local multi-process
shape instead.
"""

import base64
import json
import os
import signal
import subprocess
import sys
import time
from argparse import ArgumentParser, REMAINDER

from deepspeed_tpu.launcher.constants import DEFAULT_COORDINATOR_PORT
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = ArgumentParser(
        description="per-host deepspeed_tpu launcher (spawned by the "
        "runner on every host)")
    parser.add_argument("--node_rank", type=str, default="0",
                        help="This host's index in the world-info dict, or "
                        "'ompi' to read it from OMPI_COMM_WORLD_RANK.")
    parser.add_argument("--coordinator_addr", type=str, default="127.0.0.1")
    parser.add_argument("--coordinator_port", type=int,
                        default=DEFAULT_COORDINATOR_PORT)
    parser.add_argument("--world_info", type=str, default="None",
                        help="base64-encoded {host: [chip ids]} dict")
    parser.add_argument("--supervise", action="store_true",
                        help="single-node worlds only: run the worker "
                        "under the fault-tolerance supervisor (ISSUE "
                        "15) — restart on crash/hang from the latest "
                        "valid snapshot, bounded by --max_restarts")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--hang_deadline", type=float, default=300.0,
                        help="supervisor heartbeat-staleness deadline "
                        "(workers' in-collective deadline comes from "
                        "their fault_tolerance config block)")
    parser.add_argument("--heartbeat_dir", type=str, default="",
                        help="per-rank heartbeat directory (default: "
                        "./.dstpu_supervisor)")
    parser.add_argument("--dump_dir", type=str, default="",
                        help="supervisor watchdog dump directory "
                        "(rank_dead / crash_loop incident dumps)")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=REMAINDER)
    return parser.parse_args(args=args)


def build_child_env(args, environ=None):
    """Worker env: coordinator rendezvous + chip visibility for this host."""
    env = dict(os.environ if environ is None else environ)
    assert args.world_info != "None", "must provide world info dict"
    world_info = json.loads(base64.urlsafe_b64decode(args.world_info))

    node_list = list(world_info.keys())
    if args.node_rank == "ompi":
        node_rank = int(env["OMPI_COMM_WORLD_RANK"])
    else:
        node_rank = int(args.node_rank)
    local_node = node_list[node_rank]
    local_chip_ids = world_info[local_node]

    env["DSTPU_COORDINATOR_ADDR"] = args.coordinator_addr
    env["DSTPU_COORDINATOR_PORT"] = str(args.coordinator_port)
    env["DSTPU_NUM_PROCESSES"] = str(len(node_list))
    env["DSTPU_PROCESS_ID"] = str(node_rank)
    env["DSTPU_LOCAL_DEVICE_IDS"] = ",".join(map(str, local_chip_ids))
    # visibility narrowing for partial-host runs (the TPU runtime reads
    # TPU_VISIBLE_CHIPS; harmless elsewhere)
    if local_chip_ids:
        env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, local_chip_ids))
    return env, node_rank, len(node_list)


def main(args=None):
    args = parse_args(args)
    env, node_rank, nnodes = build_child_env(args)
    logger.info(f"node_rank={node_rank} nnodes={nnodes} "
                f"coordinator={args.coordinator_addr}:"
                f"{args.coordinator_port}")

    cmd = [sys.executable, "-u", args.training_script] \
        + args.training_script_args

    if args.supervise:
        if nnodes != 1:
            raise ValueError(
                "--supervise needs a single-node world: this per-host "
                "launcher cannot re-rendezvous hosts it does not own "
                "(use the supervisor CLI on the coordinator host, or "
                "drop --supervise for fail-fast)")
        from deepspeed_tpu.runtime.elastic.supervisor import Supervisor
        hb_dir = args.heartbeat_dir or os.path.join(
            os.getcwd(), ".dstpu_supervisor")
        sup = Supervisor(
            cmd, world=1, heartbeat_dir=hb_dir,
            dump_dir=args.dump_dir or None,
            hang_deadline_s=args.hang_deadline,
            max_restarts=args.max_restarts,
            env=env)

        # keep the fail-fast path's signal contract: SIGTERM/SIGINT to
        # the launcher must tear the supervised worker down (Python's
        # default disposition would kill us mid-run() and orphan it)
        sup.install_signal_handlers()
        sys.exit(sup.run())

    processes = []
    last_return_code = None

    def sigkill_handler(signum, frame):
        for p in processes:
            logger.info(f"Killing subprocess {p.pid}")
            try:
                p.kill()
            except Exception:
                pass
        if last_return_code is not None:
            raise subprocess.CalledProcessError(
                returncode=last_return_code, cmd=cmd)
        sys.exit(1)

    signal.signal(signal.SIGINT, sigkill_handler)
    signal.signal(signal.SIGTERM, sigkill_handler)

    processes.append(subprocess.Popen(cmd, env=env))

    alive = set(processes)
    while alive:
        finished = set()
        for p in alive:
            if p.poll() is None:
                continue
            if p.returncode != 0:
                last_return_code = p.returncode
                sigkill_handler(signal.SIGTERM, None)
            finished.add(p)
        alive -= finished
        if alive:
            time.sleep(1)


if __name__ == "__main__":
    main()
