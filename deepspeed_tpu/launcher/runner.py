"""Multi-host job front-end — rebuild of deepspeed/launcher/runner.py.

Parses an MPI-style hostfile (``worker-0 slots=4``), applies include/exclude
filters (reference runner.py:151-241), b64-encodes the resulting world info,
then either launches locally (single host) or hands the per-host command to a
multinode runner (ssh / pdsh / mpirun — reference multinode_runner.py).

TPU-first deltas from the reference:
 - "slots" are TPU chips. One *process per host* owns all of its chips (the
   JAX process model), so the per-host launcher spawns one worker by default
   instead of one per slot; chip visibility is narrowed per the slot filter
   via ``TPU_VISIBLE_CHIPS``-style env (``DSTPU_LOCAL_DEVICE_IDS``).
 - rendezvous is ``jax.distributed.initialize`` against a coordinator
   address, not a torch MASTER_ADDR store.
 - forwarded env prefixes are JAX/XLA/LIBTPU/TPU (constants.py), not NCCL/UCX.
"""

import argparse
import base64
import collections
import json
import os
import subprocess
import sys
from copy import deepcopy

from deepspeed_tpu.launcher.constants import (
    DEFAULT_COORDINATOR_PORT,
    ENVIRONMENT_FILE_NAME,
    EXPORT_ENV_PREFIXES,
    OPENMPI_LAUNCHER,
    PDSH_LAUNCHER,
    SSH_LAUNCHER,
)
from deepspeed_tpu.launcher.multinode_runner import (
    OpenMPIRunner,
    PDSHRunner,
    SSHRunner,
)
from deepspeed_tpu.utils.logging import logger

DEFAULT_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu runner: launch a training job across "
        "one or more TPU-VM hosts (reference: the `deepspeed` CLI).")
    parser.add_argument("-H", "--hostfile", type=str, default=DEFAULT_HOSTFILE,
                        help="MPI-style hostfile: lines of 'host slots=N' "
                        "where N is the chip count on that host.")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="NODE_SPEC[@NODE_SPEC ...] with "
                        "NODE_SPEC=NAME[:SLOT[,SLOT ...]] — hosts/chips to "
                        "use. Omitting :SLOT takes the whole host.")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Same syntax as --include; resources to skip. "
                        "Mutually exclusive with --include.")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Use the top N hosts of the hostfile.")
    parser.add_argument("--num_chips", type=int, default=-1,
                        help="Max chips to use per host ([0:N)).")
    parser.add_argument("--coordinator_port", type=int,
                        default=DEFAULT_COORDINATOR_PORT,
                        help="Port for the JAX distributed coordinator.")
    parser.add_argument("--coordinator_addr", type=str, default="",
                        help="Address of the coordinator (host 0); inferred "
                        "from the hostfile if unset.")
    parser.add_argument("--launcher", type=str, default=SSH_LAUNCHER,
                        help="Multi-node backend: ssh (default), pdsh, "
                        "openmpi.")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="Extra args passed through to the backend.")
    parser.add_argument("--force_multi", action="store_true",
                        help="Force multi-node code path for a single "
                        "remote host.")
    parser.add_argument("user_script", type=str,
                        help="Training script to launch.")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """'host slots=N' lines → OrderedDict host→slot-count; None if absent."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"No hostfile at {hostfile_path}; using local "
                       "resources only.")
        return None
    resource_pool = collections.OrderedDict()
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(
                    f"Hostfile line not 'host slots=N': {line!r}")
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Filter {host: [slot ids]} by an include or exclude NODE_SPEC string.

    Semantics of reference runner.py:151-241: the two are mutually
    exclusive; include builds the set from scratch, exclude removes from the
    full set; hosts left with zero slots drop out; hostfile order is kept.
    """
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")
    if not include_str and not exclude_str:
        return host_info

    filtered = {}
    parse_str = include_str
    if exclude_str:
        filtered = deepcopy(host_info)
        parse_str = exclude_str

    for node_config in parse_str.split("@"):
        if ":" in node_config:
            hostname, slot_str = node_config.split(":")
            slots = [int(x) for x in slot_str.split(",")]
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not in hostfile")
            for s in slots:
                if s not in host_info[hostname]:
                    raise ValueError(
                        f"No slot '{s}' on host '{hostname}'")
            if include_str:
                filtered[hostname] = slots
            else:
                for s in slots:
                    filtered[hostname].remove(s)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not in hostfile")
            if include_str:
                filtered[hostname] = host_info[hostname]
            else:
                filtered[hostname] = []

    for hostname in list(filtered):
        filtered[hostname] = sorted(set(filtered[hostname]))
        if not filtered[hostname]:
            del filtered[hostname]

    ordered = collections.OrderedDict()
    for host in host_info:
        if host in filtered:
            ordered[host] = filtered[host]
    return ordered


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    active = collections.OrderedDict(
        (host, list(range(slots))) for host, slots in resource_pool.items())
    return parse_resource_filter(active, include_str=inclusion,
                                 exclude_str=exclusion)


def encode_world_info(world_info):
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded))


def _local_chip_count():
    """Count local TPU chips WITHOUT initializing a JAX backend: libtpu
    takes an exclusive per-process lock, so touching jax here would leave
    the launcher holding the TPU and the spawned training process unable
    to acquire it. Device files are authoritative on TPU-VMs."""
    import glob
    for pattern in ("/dev/accel*", "/dev/vfio/[0-9]*"):
        chips = glob.glob(pattern)
        if chips:
            return len(chips)
    return 1


def collect_exports(environ=None):
    """Env vars to forward to workers, by prefix + per-job env file."""
    environ = os.environ if environ is None else environ
    exports = {}
    for key, val in environ.items():
        if any(key.startswith(p) for p in EXPORT_ENV_PREFIXES):
            exports[key] = val
    for path in (os.path.expanduser("~"), "."):
        env_file = os.path.join(path, ENVIRONMENT_FILE_NAME)
        if os.path.isfile(env_file):
            with open(env_file) as fd:
                for line in fd:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        key, val = line.split("=", 1)
                        exports[key.strip()] = val.strip()
    return exports


def main(args=None):
    args = parse_args(args)

    if (args.num_nodes >= 0 or args.num_chips >= 0) and \
            (args.include or args.exclude):
        raise ValueError(
            "Cannot specify num_nodes/num_chips with include/exclude")

    multi_node = True
    resource_pool = fetch_hostfile(args.hostfile)
    if not resource_pool:
        resource_pool = collections.OrderedDict(
            localhost=_local_chip_count())
        args.coordinator_addr = "127.0.0.1"
        multi_node = False
    if not multi_node and args.num_nodes > 1:
        raise ValueError("num_nodes > 1 but hostfile provides one host")

    active_resources = parse_inclusion_exclusion(resource_pool,
                                                 args.include, args.exclude)
    if args.num_nodes > 0:
        keep = list(active_resources.keys())[:args.num_nodes]
        active_resources = collections.OrderedDict(
            (k, active_resources[k]) for k in keep)
    if args.num_chips > 0:
        for host in active_resources:
            active_resources[host] = \
                active_resources[host][:args.num_chips]

    if not args.coordinator_addr:
        args.coordinator_addr = next(iter(active_resources))

    world_info = encode_world_info(
        {h: s for h, s in active_resources.items()})

    # A hostfile naming only this machine still runs locally (no sshd
    # needed) unless --force_multi asks for the remote path.
    if multi_node and len(active_resources) == 1 and \
            next(iter(active_resources)) in ("localhost", "127.0.0.1"):
        multi_node = False
    multi_node = multi_node or args.force_multi
    env = os.environ.copy()
    if not multi_node:
        # Single host: exec the per-host launcher directly. The per-job env
        # file applies here too (same contract as the multi-node path).
        env.update(collect_exports())
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={world_info}", "--node_rank=0",
               f"--coordinator_addr={args.coordinator_addr}",
               f"--coordinator_port={args.coordinator_port}",
               args.user_script] + args.user_args
    else:
        runner_cls = {SSH_LAUNCHER: SSHRunner, PDSH_LAUNCHER: PDSHRunner,
                      OPENMPI_LAUNCHER: OpenMPIRunner}.get(
                          args.launcher.lower())
        if runner_cls is None:
            raise ValueError(f"Unknown launcher {args.launcher}")
        runner = runner_cls(args, world_info)
        if not runner.backend_exists():
            raise RuntimeError(
                f"launcher backend '{args.launcher}' not installed")
        for key, val in collect_exports().items():
            runner.add_export(key, val)
        # get_cmd may mutate env (e.g. PDSH_RCMD_TYPE); the same dict goes
        # to Popen below.
        cmd = runner.get_cmd(env, active_resources)

    logger.info(f"cmd = {' '.join(map(str, cmd))}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    if result.returncode != 0:
        sys.exit(result.returncode)


if __name__ == "__main__":
    main()
