"""Injection policies — reference module_inject/replace_policy.py.

A policy knows how to read the parameters out of a client transformer layer
(HF flax BERT, Megatron-style fused-QKV layers, or this repo's own fused
layer) and hand them to replace_module in the canonical fused-layer order.
The reference's policies return torch tensors off live nn.Modules
(replace_policy.py:32 HFBertLayerPolicy, :103 MegatronLayerPolicy); here a
policy maps a param *subtree* (flax pytrees are the module state) to the
fused layer's param names.
"""

import jax.numpy as jnp


class DSPolicy:
    """Base policy: subclasses define how to extract (qkv, attn out, mlp,
    layernorms) from one source layer's param subtree."""
    # does the source architecture normalize before (True) or after (False)
    # each sublayer
    pre_attn_norm = False

    def attention(self, layer):
        """→ (qkv_kernel [E,3E], qkv_bias [3E], out_kernel [E,E], out_bias)"""
        raise NotImplementedError

    def mlp(self, layer):
        """→ (inter_kernel, inter_bias, out_kernel, out_bias)"""
        raise NotImplementedError

    def layernorm(self, layer):
        """→ (attn_ln_scale, attn_ln_bias, ffn_ln_scale, ffn_ln_bias)"""
        raise NotImplementedError


class HFBertLayerPolicy(DSPolicy):
    """HF flax BERT layer subtree (encoder/layer/<i>): separate q/k/v denses,
    post-LN (reference replace_policy.py:32-100)."""
    pre_attn_norm = False

    def attention(self, layer):
        a = layer["attention"]["self"]
        qkv_kernel = jnp.concatenate(
            [a["query"]["kernel"], a["key"]["kernel"], a["value"]["kernel"]],
            axis=1)
        qkv_bias = jnp.concatenate(
            [a["query"]["bias"], a["key"]["bias"], a["value"]["bias"]])
        o = layer["attention"]["output"]["dense"]
        return qkv_kernel, qkv_bias, o["kernel"], o["bias"]

    def mlp(self, layer):
        i = layer["intermediate"]["dense"]
        o = layer["output"]["dense"]
        return i["kernel"], i["bias"], o["kernel"], o["bias"]

    def layernorm(self, layer):
        attn_ln = layer["attention"]["output"]["LayerNorm"]
        ffn_ln = layer["output"]["LayerNorm"]
        return attn_ln["scale"], attn_ln["bias"], ffn_ln["scale"], \
            ffn_ln["bias"]


class MegatronLayerPolicy(DSPolicy):
    """Megatron-style layer subtree: fused query_key_value dense, pre-LN
    (reference replace_policy.py:103-144)."""
    pre_attn_norm = True

    def attention(self, layer):
        qkv = layer["attention"]["query_key_value"]
        o = layer["attention"]["dense"]
        return qkv["kernel"], qkv["bias"], o["kernel"], o["bias"]

    def mlp(self, layer):
        i = layer["mlp"]["dense_h_to_4h"]
        o = layer["mlp"]["dense_4h_to_h"]
        return i["kernel"], i["bias"], o["kernel"], o["bias"]

    def layernorm(self, layer):
        attn_ln = layer["input_layernorm"]
        ffn_ln = layer["post_attention_layernorm"]
        return attn_ln["scale"], attn_ln["bias"], ffn_ln["scale"], \
            ffn_ln["bias"]


class DSTransformerLayerPolicy(DSPolicy):
    """Identity policy over this repo's own fused layer params (useful for
    training→inference injection and for revert)."""
    def __init__(self, pre_layer_norm=True):
        self.pre_attn_norm = pre_layer_norm

    def attention(self, layer):
        return layer["attn_qkvw"]["kernel"], layer["attn_qkvw"]["bias"], \
            layer["attn_ow"]["kernel"], layer["attn_ow"]["bias"]

    def mlp(self, layer):
        return layer["inter_w"]["kernel"], layer["inter_w"]["bias"], \
            layer["output_w"]["kernel"], layer["output_w"]["bias"]

    def layernorm(self, layer):
        return layer["attn_nw"]["scale"], layer["attn_nw"]["bias"], \
            layer["norm_w"]["scale"], layer["norm_w"]["bias"]
