"""Module injection — reference module_inject/replace_module.py:8
`replace_transformer_layer` and module_inject/inject.py.

In torch the reference walks a live model and swaps nn.Module objects for
fused-kernel layers, copying weights tensor-by-tensor. In flax the module
tree is a pure definition and the state is a pytree, so injection is a pytree
transformation: a policy reads each source layer subtree, emits the fused
layer's params, and the caller runs the fused model definition
(DeepSpeedTransformerLayer for training, DeepSpeedTransformerInference for
serving) over the converted params.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer import DeepSpeedTransformerConfig
from deepspeed_tpu.ops.transformer.inference import DeepSpeedInferenceConfig
from deepspeed_tpu.module_inject.replace_policy import (
    DSPolicy, HFBertLayerPolicy, MegatronLayerPolicy)


def inject_layer_params(policy: DSPolicy, layer_params) -> dict:
    """One source layer subtree → fused-layer params (the weight-copy loop of
    reference replace_module.py:24-79, as a pure function)."""
    qkv_k, qkv_b, ow_k, ow_b = policy.attention(layer_params)
    in_k, in_b, out_k, out_b = policy.mlp(layer_params)
    attn_s, attn_b, ffn_s, ffn_b = policy.layernorm(layer_params)
    return {
        "attn_qkvw": {"kernel": qkv_k, "bias": qkv_b},
        "attn_ow": {"kernel": ow_k, "bias": ow_b},
        "inter_w": {"kernel": in_k, "bias": in_b},
        "output_w": {"kernel": out_k, "bias": out_b},
        "attn_nw": {"scale": attn_s, "bias": attn_b},
        "norm_w": {"scale": ffn_s, "bias": ffn_b},
    }


def revert_layer_params(fused_params, policy: DSPolicy) -> dict:
    """Inverse of inject_layer_params for HF BERT layout (reference
    revert_transformer_layer, replace_module.py:81-120)."""
    if not isinstance(policy, HFBertLayerPolicy):
        raise NotImplementedError("revert supports the HF BERT layout")
    qkv_k = fused_params["attn_qkvw"]["kernel"]
    qkv_b = fused_params["attn_qkvw"]["bias"]
    E = qkv_k.shape[0]
    qk, kk, vk = jnp.split(qkv_k, 3, axis=1)
    qb, kb, vb = jnp.split(qkv_b, 3)
    return {
        "attention": {
            "self": {"query": {"kernel": qk, "bias": qb},
                     "key": {"kernel": kk, "bias": kb},
                     "value": {"kernel": vk, "bias": vb}},
            "output": {"dense": {"kernel": fused_params["attn_ow"]["kernel"],
                                 "bias": fused_params["attn_ow"]["bias"]},
                       "LayerNorm": {"scale": fused_params["attn_nw"]["scale"],
                                     "bias": fused_params["attn_nw"]["bias"]}},
        },
        "intermediate": {"dense": {"kernel": fused_params["inter_w"]["kernel"],
                                   "bias": fused_params["inter_w"]["bias"]}},
        "output": {"dense": {"kernel": fused_params["output_w"]["kernel"],
                             "bias": fused_params["output_w"]["bias"]},
                   "LayerNorm": {"scale": fused_params["norm_w"]["scale"],
                                 "bias": fused_params["norm_w"]["bias"]}},
    }


def quantize_transformer_layer(fused_params, bits=8, groups=1):
    """Fake-quantize the four weight matrices of a fused layer subtree — the
    role of module_inject/module_quantize.py (the reference quantizes
    injected weights through the quantizer kernel; int8-storage serving uses
    ops.quantizer.quantize_packed). Shares the grouped-quantization math
    with MoQ/serving via ops.quantizer."""
    from deepspeed_tpu.ops.quantizer import quantize_jnp
    out = jax.tree_util.tree_map(lambda x: x, fused_params)
    for name in ("attn_qkvw", "attn_ow", "inter_w", "output_w"):
        out[name] = dict(out[name])
        out[name]["kernel"] = quantize_jnp(
            out[name]["kernel"], bits=bits, groups=groups, sym=True)
    return out


def _find_layer_container(params):
    """Locate the HF-style encoder layer dict {'0': subtree, '1': ...}."""
    if "encoder" in params and "layer" in params["encoder"]:
        return params["encoder"]["layer"]
    if "layer" in params:
        return params["layer"]
    raise ValueError("could not find encoder.layer container in params; "
                     "pass layer_params explicitly")


def replace_transformer_layer(policy_cls,
                              model_params,
                              config: Optional[Any] = None,
                              fp16: bool = False,
                              training: bool = True,
                              quantize: bool = False,
                              quantize_bits: int = 8,
                              quantize_groups: int = 1,
                              mp_size: int = 1,
                              max_out_tokens: int = 1024,
                              preln: Optional[bool] = None):
    """Convert a client model's params for the fused layer — reference
    replace_transformer_layer (module_inject/replace_module.py:8).

    Arguments:
        policy_cls: a DSPolicy subclass (or instance) describing the source
            layer layout.
        model_params: the client model's full param pytree (HF flax style,
            with encoder.layer.<i> children) or a list of layer subtrees.
        config: the client model config (HF BertConfig-like) used to build
            the fused config; optional if you only need the params.
        training/fp16/quantize/mp_size: reference knobs; training selects
            DeepSpeedTransformerConfig vs DeepSpeedInferenceConfig.

    Returns:
        (fused_config, layer_params_list) — fused params for layer i under
        the returned config's layer module.
    """
    policy = policy_cls() if isinstance(policy_cls, type) else policy_cls
    if isinstance(model_params, (list, tuple)):
        layers = list(model_params)
    else:
        container = _find_layer_container(model_params)
        layers = [container[k] for k in
                  sorted(container.keys(), key=lambda s: int(s))]

    converted = [inject_layer_params(policy, l) for l in layers]
    if quantize:
        converted = [quantize_transformer_layer(c, quantize_bits,
                                                quantize_groups)
                     for c in converted]

    pre_ln = policy.pre_attn_norm if preln is None else preln
    hidden = int(converted[0]["attn_qkvw"]["kernel"].shape[0])
    inter = int(converted[0]["inter_w"]["kernel"].shape[1])
    heads = getattr(config, "num_attention_heads", None) or \
        getattr(config, "heads", None) or max(1, hidden // 64)
    eps = getattr(config, "layer_norm_eps", 1e-12)

    if training:
        fused_cfg = DeepSpeedTransformerConfig(
            hidden_size=hidden, intermediate_size=inter, heads=heads,
            num_hidden_layers=len(converted), layer_norm_eps=eps,
            pre_layer_norm=pre_ln, fp16=fp16)
    else:
        fused_cfg = DeepSpeedInferenceConfig(
            hidden_size=hidden, intermediate_size=inter, heads=heads,
            layer_norm_eps=eps, pre_layer_norm=pre_ln, fp16=fp16,
            mp_size=mp_size, triangular_masking=False,
            max_out_tokens=max_out_tokens)
    return fused_cfg, converted


def convert_hf_bert(hf_params, hf_config, fp16: bool = False,
                    scan_layers: bool = False):
    """Whole-model conversion: HF flax BERT params → this repo's BertModel
    (models/bert.py) definition + params. Returns (BertConfig, params).

    This is the end-to-end injection path a reference user gets from
    replace_transformer_layer(HFBertLayerPolicy, model, ...): afterwards the
    model runs entirely on fused layers.
    """
    from deepspeed_tpu.models.bert import BertConfig

    cfg = BertConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_hidden_layers=hf_config.num_hidden_layers,
        num_attention_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position_embeddings=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        hidden_dropout_prob=getattr(hf_config, "hidden_dropout_prob", 0.0),
        attention_probs_dropout_prob=getattr(
            hf_config, "attention_probs_dropout_prob", 0.0),
        layer_norm_eps=getattr(hf_config, "layer_norm_eps", 1e-12),
        pre_layer_norm=False,
        dtype=jnp.bfloat16 if fp16 else jnp.float32,
        scan_layers=scan_layers,
    )
    _, layers = replace_transformer_layer(
        HFBertLayerPolicy, hf_params, config=hf_config, fp16=fp16)

    emb = hf_params["embeddings"]
    params = {
        "embeddings": {
            "word_embeddings": emb["word_embeddings"]["embedding"],
            "position_embeddings": emb["position_embeddings"]["embedding"],
            "token_type_embeddings": emb["token_type_embeddings"]["embedding"],
            "LayerNorm": {"scale": emb["LayerNorm"]["scale"],
                          "bias": emb["LayerNorm"]["bias"]},
        },
        "encoder": {},
        "pooler": {"kernel": hf_params["pooler"]["dense"]["kernel"],
                   "bias": hf_params["pooler"]["dense"]["bias"]},
    }
    if scan_layers:
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
        params["encoder"] = {"layer": {"DeepSpeedTransformerLayer_0": stacked}}
    else:
        for i, l in enumerate(layers):
            params["encoder"][f"DeepSpeedTransformerLayer_{i}"] = l
    return cfg, params
