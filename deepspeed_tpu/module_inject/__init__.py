"""Module injection (reference deepspeed/module_inject)."""

from deepspeed_tpu.module_inject.replace_policy import (
    DSPolicy,
    HFBertLayerPolicy,
    MegatronLayerPolicy,
    DSTransformerLayerPolicy,
)
from deepspeed_tpu.module_inject.replace_module import (
    replace_transformer_layer,
    revert_layer_params,
    inject_layer_params,
    quantize_transformer_layer,
    convert_hf_bert,
)

__all__ = [
    "DSPolicy",
    "HFBertLayerPolicy",
    "MegatronLayerPolicy",
    "DSTransformerLayerPolicy",
    "replace_transformer_layer",
    "revert_layer_params",
    "inject_layer_params",
    "quantize_transformer_layer",
    "convert_hf_bert",
]
