from deepspeed_tpu.parallel.mesh import MeshConfig, make_mesh, init_distributed
from deepspeed_tpu.parallel.topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
)
