"""ZeRO-3 layer-wise parameter-gather prefetch pipeline.

The fused GSPMD stage-3 path expresses every per-layer parameter
all-gather implicitly (a sharding constraint at rest, the gathers
materialize wherever XLA schedules them) — which leaves XLA free to
serialize the whole gather stream before compute. The reference
DeepSpeed instead prefetches: the PartitionedParameterCoordinator
(stage3.py:287-447) gathers the NEXT submodule's partitions while the
current one computes, bounded by ``stage3_max_live_parameters``. This
module is the TPU-native rebuild of that coordinator as an explicit
shard_map program:

  * layer-stacked parameter shards (leading dim = layer) pack into ONE
    flat buffer per layer (the prefetch "bucket" — like the IPG buckets
    of parallel/overlap.py, but for params);
  * the forward is a ``lax.scan`` whose carry holds the IN-FLIGHT
    gathered buffer: iteration *i* issues the ring all-gather of layer
    *i+1*'s shards and computes layer *i* from the buffer gathered one
    iteration earlier (double buffering). The gather has no data
    dependency on the compute, so XLA's latency-hiding scheduler floats
    the hops over the layer's matmuls;
  * gathered params DROP at the end of their iteration: live full
    parameters are bounded at ~2 layers (+ the small persistent
    remainder) — the TPU-native ``stage3_max_live_parameters``;
  * the backward (a ``jax.custom_vjp``) re-gathers each layer in
    REVERSE order with the same double buffering, and reduce-scatters
    each layer's parameter gradient (the PR-1 ring of
    parallel/overlap.py) inside the same iteration — the ring is busy
    in both directions while the layer's VJP computes. Layer inputs are
    the only saved residuals, so each layer's forward rematerializes in
    backward (full-remat semantics, same memory shape as the
    reference's post-backward partition release).

Everything here is pure, jit-able, and must run INSIDE ``shard_map``
binding ``axis_name`` (the engine's ``stage3_prefetch`` train path).
Gradients of sharded leaves come back reduce-scattered as SUMS over the
axis (the caller normalizes to a mean); gradients of replicated leaves
come back LOCAL (the caller runs them through
``overlap.bucketed_allreduce``, composing with ``overlap_comm``).
"""

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import overlap as overlap_lib


# ---------------------------------------------------------------------------
# plan (host-side, static)
# ---------------------------------------------------------------------------

def plan_from_specs(leaves, specs, axis_name: str, n: int):
    """Per-leaf shard plan from a PartitionSpec tree: ``(dim, shard_size)``
    where ``dim`` (in the leaf's own coordinates) carries ``axis_name``,
    or None for leaves the spec leaves replicated over the axis — the
    same contract as ``ZeroPartitioner.explicit_shard_plan``, usable on
    any params subtree."""
    plan = []
    for leaf, spec in zip(leaves, specs):
        entry = None
        for d, ax in enumerate(spec):
            axes = ax if isinstance(ax, tuple) else (ax,)
            if axis_name in axes:
                entry = (d, leaf.shape[d] // n)
                break
        plan.append(entry)
    return plan


@dataclasses.dataclass(frozen=True)
class LayerPlan:
    """Static packing plan for one layer-stacked params subtree.

    ``plan`` entries are in PER-LAYER leaf coordinates (the stacked
    leaf's dim minus the leading layer dim); sharded leaves group by
    dtype into packed flat buffers (one ring gather per group per
    layer), replicated leaves ride the scan as sliced inputs.
    ``fused`` ids (ISSUE 8) are sharded leaves EXCLUDED from the packed
    gather: they ride the scan as resting shards and the model body
    streams them chunk-by-chunk through the tile-granular fused
    matmul+collective kernels (ops/pallas/fused_collective.py) — their
    gradients come back from the body's custom VJPs already
    reduce-scattered (shard-shaped SUMS over the axis)."""
    plan: Tuple[Optional[Tuple[int, int]], ...]
    # (dtype, leaf_ids) per packed group — leaf order within a group is
    # the flattened-tree order, offsets implied by cumulative sizes
    groups: Tuple[Tuple[Any, Tuple[int, ...]], ...]
    n: int
    fused: Tuple[int, ...] = ()

    @property
    def sharded_ids(self):
        return tuple(i for g in self.groups for i in g[1])


def _collective_mode(mode: str) -> str:
    """The plain-collective mode backing ``mode``: fused_matmul leaves
    riding the packed gather (below-threshold / non-2D), the outer
    step-persistent gathers, and the replicated-leaf bucket stream all
    exchange via the explicit ppermute ring."""
    return "ring" if mode == "fused_matmul" else mode


def build_layer_plan(shard_leaves, plan, n: int,
                     fused_ids=()) -> LayerPlan:
    """``shard_leaves``: per-device stacked shards ([L, ...]);
    ``plan``: entries in STACKED coordinates (dim 0 is the layer dim and
    must never be sharded — the partitioner's ``layer_stacked_prefixes``
    guarantees it). ``fused_ids`` (leaf indices, engine-selected) skip
    the packed groups — see LayerPlan.fused."""
    per_layer = []
    groups = {}
    fused = tuple(sorted(fused_ids))
    for i, (leaf, entry) in enumerate(zip(shard_leaves, plan)):
        if entry is None:
            assert i not in fused, \
                f"fused leaf {i} is not sharded — engine selection bug"
            per_layer.append(None)
            continue
        d, sz = entry
        assert d >= 1, (
            f"layer-stacked leaf {i} sharded on its layer dim (shape "
            f"{leaf.shape}); exclude dim 0 via layer_stacked_prefixes")
        per_layer.append((d - 1, sz))
        if i not in fused:
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    lp = LayerPlan(plan=tuple(per_layer),
                   groups=tuple((dt, tuple(ids))
                                for dt, ids in groups.items()),
                   n=n, fused=fused)
    # flight-recorder breadcrumb (trace-time only — the plan is built
    # once per compile): the per-layer gather shape of this train fn
    from deepspeed_tpu.telemetry.recorder import default_recorder
    default_recorder().record(
        "prefetch_layer_plan", groups=len(lp.groups),
        sharded_leaves=len(lp.sharded_ids), fused_leaves=len(fused),
        replicated_leaves=sum(1 for e in lp.plan if e is None),
        axis_size=n)
    return lp


# ---------------------------------------------------------------------------
# chunk-major leaf <-> flat packing (per-device; inside shard_map)
# ---------------------------------------------------------------------------

def _full_from_chunks(chunks, d):
    """[n, *shard_shape] (chunk j = device j's slice of dim ``d``) → full
    leaf with dim ``d`` of size n*shard."""
    full = jnp.moveaxis(chunks, 0, d)          # [..., n, shard, ...]
    shape = list(full.shape)
    shape[d:d + 2] = [shape[d] * shape[d + 1]]
    return full.reshape(shape)


def _chunks_from_full(full, d, n):
    """Inverse of ``_full_from_chunks``: full leaf → [n, *shard_shape]."""
    shape = list(full.shape)
    shape[d:d + 1] = [n, shape[d] // n]
    return jnp.moveaxis(full.reshape(shape), d, 0)


def gather_leaf(shard, entry, axis_name: str, n: int, mode: str = "ring",
                hier=None):
    """All-gather one sharded leaf ((dim, size) entry) to its full shape.
    mode="ring": explicit ppermute ring (overlap.ring_all_gather);
    mode="fused": one ``lax.all_gather`` (XLA picks the algorithm);
    mode="fused_matmul" gathers like "ring" — leaves that reach this
    function in that mode were NOT selected for fused streaming.
    ``hier`` (an overlap.HierarchyPlan, ISSUE 16) replaces the flat ring
    with the two-level schedule: ONE slow-hop all-gather of the raw
    shard, fast intra ring for the rest — ``axis_name`` is then unused
    (the split mesh binds the plan's axes instead)."""
    if entry is None or n == 1:
        return shard
    d, _ = entry
    if hier is not None:
        flat = overlap_lib.two_level_all_gather(shard.reshape(-1), hier)
        return _full_from_chunks(flat.reshape((n,) + shard.shape), d)
    mode = _collective_mode(mode)
    if mode == "fused":
        return jax.lax.all_gather(shard, axis_name, axis=d, tiled=True)
    flat = overlap_lib.ring_all_gather(shard.reshape(-1), axis_name, n)
    return _full_from_chunks(flat.reshape((n,) + shard.shape), d)


def scatter_grad(grad_full, entry, axis_name: str, n: int,
                 mode: str = "ring", hier=None):
    """Reduce-scatter one full-leaf gradient back to this device's shard
    (SUM over the axis), in fp32 — the transpose of ``gather_leaf``.
    Under ``hier`` the slow hop is the EXACT two-level ring (the
    compressed outer leg threads error state and lives in
    `make_gathered_param_with_error` instead)."""
    if entry is None or n == 1:
        return grad_full
    d, _ = entry
    chunks = _chunks_from_full(grad_full.astype(jnp.float32), d, n)
    if hier is not None:
        return overlap_lib.two_level_reduce_scatter_sum(
            chunks.reshape(n, -1), hier).reshape(chunks.shape[1:])
    mode = _collective_mode(mode)
    if mode == "fused":
        return jax.lax.psum_scatter(chunks.reshape(-1), axis_name,
                                    scatter_dimension=0, tiled=True) \
            .reshape(chunks.shape[1:])
    return overlap_lib.ring_reduce_scatter(
        chunks.reshape(-1), axis_name, n).reshape(chunks.shape[1:])


def scatter_grad_with_error(grad_full, entry, n: int, err, hier):
    """Compressed-slow-hop counterpart of ``scatter_grad`` (ISSUE 16):
    reduce-scatter a full-leaf gradient with error-compensated sign bits
    on the inter-host hop. ``err`` is the persistent per-device
    [`outer_error_numel(shard_numel, hier)`] fp32 residual. Returns
    (grad_shard fp32 SUM, new_err)."""
    d, _ = entry
    chunks = _chunks_from_full(grad_full.astype(jnp.float32), d, n)
    piece, new_err = overlap_lib.two_level_reduce_scatter_compressed(
        chunks.reshape(n, -1), err, hier)
    return piece.reshape(chunks.shape[1:]), new_err


def _gather_groups(group_bufs, axis_name, n, mode, hier=None):
    """Per-group packed shard [K_g] → gathered [n, K_g] (row j = device
    j's shard) — ONE collective per group per layer (two under ``hier``:
    the slow-hop all-gather + the fast intra ring). fused_matmul mode
    gathers its residual (non-streamed) groups like ring."""
    if hier is not None:
        return tuple(overlap_lib.two_level_all_gather(buf, hier)
                     for buf in group_bufs)
    mode = _collective_mode(mode)
    out = []
    for buf in group_bufs:
        if mode == "fused":
            out.append(jax.lax.all_gather(buf, axis_name))
        else:
            out.append(overlap_lib.ring_all_gather(buf, axis_name, n)
                       .reshape(n, buf.size))
    return tuple(out)


def _unpack_layer_full(gathered, shard_leaves, layer_plan: LayerPlan):
    """Per-group gathered [n, K_g] buffers → full per-layer leaves (dict
    id → array)."""
    out = {}
    for (_, ids), buf in zip(layer_plan.groups, gathered):
        off = 0
        for i in ids:
            shard_shape = shard_leaves[i].shape[1:]
            m = int(np.prod(shard_shape or (1,)))
            d, _ = layer_plan.plan[i]
            chunks = jax.lax.dynamic_slice_in_dim(buf, off, m, 1) \
                .reshape((layer_plan.n,) + shard_shape)
            out[i] = _full_from_chunks(chunks, d)
            off += m
    return out


def _scatter_layer_grads(grads_by_id, shard_leaves, layer_plan: LayerPlan,
                         axis_name, n, mode, hier=None, errs_in=None):
    """Full per-layer grad leaves → per-leaf fp32 shard grads (dict id →
    array), SUM over the axis, packed so each layer costs one
    reduce-scatter per dtype group.

    Under ``hier`` each group's exchange is the two-level schedule
    (fast-axis fp32 partial sums, ONE slow hop); a group whose
    ``errs_in`` entry is non-None compresses that slow hop to
    error-compensated sign bits. Returns (out, errs_out) with
    ``errs_out`` aligned per group (None where uncompressed)."""
    out = {}
    errs_out = []
    for g, (_, ids) in enumerate(layer_plan.groups):
        parts = []
        for i in ids:
            d, _ = layer_plan.plan[i]
            parts.append(_chunks_from_full(
                grads_by_id[i].astype(jnp.float32), d, n)
                .reshape(n, -1))
        flat = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
        err = errs_in[g] if errs_in is not None else None
        if hier is not None and err is not None:
            shard, new_err = overlap_lib.two_level_reduce_scatter_compressed(
                flat, err, hier)
            errs_out.append(new_err)
        elif hier is not None:
            shard = overlap_lib.two_level_reduce_scatter_sum(flat, hier)
            errs_out.append(None)
        elif _collective_mode(mode) == "fused":
            shard = jax.lax.psum_scatter(flat.reshape(-1), axis_name,
                                         scatter_dimension=0, tiled=True)
            errs_out.append(None)
        else:
            shard = overlap_lib.ring_reduce_scatter(
                flat.reshape(-1), axis_name, n)
            errs_out.append(None)
        off = 0
        for i in ids:
            shard_shape = shard_leaves[i].shape[1:]
            m = int(np.prod(shard_shape or (1,)))
            out[i] = jax.lax.dynamic_slice_in_dim(shard, off, m, 0) \
                .reshape(shard_shape)
            off += m
    return out, tuple(errs_out)


# ---------------------------------------------------------------------------
# the prefetched layer scan (inside shard_map)
# ---------------------------------------------------------------------------

def make_prefetched_scan(body: Callable, plan: Sequence, axis_name: str,
                         n: int, mode: str = "ring", fused_ids=(),
                         fused_cfg=None, hier=None):
    """Build ``scan_fn(x, layer_shards_tree) -> y`` running ``body(x,
    layer_params_tree)`` over the leading layer dim of
    ``layer_shards_tree`` with double-buffered parameter gathers.

    ``plan`` is aligned with ``tree_leaves(layer_shards_tree)`` in
    STACKED coordinates ((dim, shard_size), dim >= 1, or None for
    replicated leaves). ``body`` receives FULL (gathered) per-layer
    leaves and must be rng-free (the engine gates dropout off).

    mode="fused_matmul" (ISSUE 8): ``fused_ids`` leaves skip the packed
    gather and reach ``body`` as their per-layer RESTING SHARDS; the
    body's collective-matmul-aware dense layers (models/gpt2.py
    CollectiveDense, activated by the ``gather_scope(fused_cfg)``
    entered around every body trace) stream them chunk-by-chunk through
    the tile-granular fused kernels. Their gradients therefore come
    back from the body's custom VJPs ALREADY reduce-scattered
    (shard-shaped SUMS over the axis) — no _scatter_layer_grads pass.
    Remaining sharded leaves ride the packed ring gather.

    ``hier`` (an overlap.HierarchyPlan, ISSUE 16): every packed gather
    and per-layer grad reduce-scatter runs the two-level link-aware
    schedule over the plan's split axes instead of the flat ring over
    ``axis_name`` (fused leaves get theirs from ``fused_cfg.hierarchy``
    inside the body's collective kernels). With ``hier`` set the
    returned function takes a THIRD argument ``errs`` — a tuple aligned
    with the packed dtype groups (see `plan_group_errors`) holding each
    compressed group's persistent [L, E] slow-hop error state (None for
    groups the policy leaves exact) — and its custom VJP returns the
    NEW error state as the errs cotangent: the engine reads it back via
    ``jax.grad(..., argnums=...)`` and carries it in opt_state, the
    same state-through-cotangent shape the 1-bit optimizer uses for its
    error feedback, here per layer per group.

    Custom VJP: the backward scan runs in reverse, re-gathering layer
    i-1 while layer i's VJP computes and reduce-scattering layer i's
    parameter gradients in the same iteration. Returns gradients for
    packed sharded leaves as fp32 SHARDS summed over the axis; FUSED
    leaves come back shard-shaped and summed but in the PARAM dtype
    (the matmul+RS kernel accumulates the true partial sums in fp32
    and rounds once on output — under bf16 params that is one rounding
    of an fp32 accumulation, vs the ring path's fp32 sum of
    bf16-rounded per-device grads); replicated leaves' gradients are
    LOCAL (caller reduces them).
    """
    if mode not in ("ring", "fused", "fused_matmul"):
        raise ValueError(f"mode must be 'ring', 'fused' or "
                         f"'fused_matmul', got {mode!r}")
    if fused_ids and mode != "fused_matmul":
        raise ValueError("fused_ids requires mode='fused_matmul'")
    if hier is not None and mode == "fused":
        raise ValueError(
            "hier requires explicit collectives (mode 'ring' or "
            "'fused_matmul') — mode='fused' hands the schedule to XLA")
    plan = tuple(tuple(e) if e is not None else None for e in plan)
    fused_ids = tuple(sorted(fused_ids))

    from deepspeed_tpu.ops.pallas import fused_collective as fc

    def _scope():
        # trace-scoped: CollectiveDense consults it wherever jax
        # (re-)traces the body — a no-op scope when nothing is fused
        return fc.gather_scope(fused_cfg if fused_ids else None)

    def _prep(layer_shards):
        leaves, tdef = jax.tree_util.tree_flatten(layer_shards)
        lp = build_layer_plan(leaves, plan, n, fused_ids=fused_ids)
        return leaves, tdef, lp

    def _layer_tree(tdef, lp, leaves, full_by_id, fused_sliced,
                    repl_sliced):
        per_layer: List[Any] = [None] * len(leaves)
        for i in lp.sharded_ids:
            per_layer[i] = full_by_id[i]
        for i, leaf in zip(lp.fused, fused_sliced):
            per_layer[i] = leaf
        for i, leaf in zip(
                (j for j, e in enumerate(lp.plan) if e is None), repl_sliced):
            per_layer[i] = leaf
        return jax.tree_util.tree_unflatten(tdef, per_layer)

    @jax.custom_vjp
    def scan_fn(x, layer_shards):
        y, _ = _forward(x, layer_shards)
        return y

    def _forward(x, layer_shards):
        leaves, tdef, lp = _prep(layer_shards)
        L = leaves[0].shape[0]
        repl_ids = [j for j, e in enumerate(lp.plan) if e is None]
        repl_stack = tuple(leaves[j] for j in repl_ids)
        fused_stack = tuple(leaves[j] for j in lp.fused)
        if not lp.groups:
            # no packed gathers (persistence threshold kept every
            # non-fused leaf replicated): a plain scan — fused shards
            # still stream through the body's collective kernels
            def step0(carry, inp):
                fused_i, repl_i = inp
                lt = _layer_tree(tdef, lp, leaves, {}, fused_i, repl_i)
                with _scope():
                    y = body(carry, lt)
                return y, carry
            y, xs_saved = jax.lax.scan(step0, x, (fused_stack, repl_stack),
                                       length=L)
            return y, (xs_saved, layer_shards)

        # stacked packed buffers: [L, K_g] per dtype group
        packed_groups = tuple(
            jnp.concatenate([leaves[i].reshape(L, -1) for i in ids], axis=1)
            if len(ids) > 1 else leaves[ids[0]].reshape(L, -1)
            for _, ids in lp.groups)
        g0 = _gather_groups(tuple(pg[0] for pg in packed_groups),
                            axis_name, n, mode, hier=hier)
        # iteration i's scan input carries layer i+1's shards (the last
        # iteration re-gathers layer 0 — one redundant gather that
        # overlaps the final layer's compute and keeps the scan uniform)
        nxt = tuple(jnp.roll(pg, -1, axis=0) for pg in packed_groups)

        def step(carry, inp):
            xc, g_cur = carry
            nxt_bufs, fused_i, repl_i = inp
            g_nxt = _gather_groups(nxt_bufs, axis_name, n, mode, hier=hier)
            full = _unpack_layer_full(g_cur, leaves, lp)
            lt = _layer_tree(tdef, lp, leaves, full, fused_i, repl_i)
            with _scope():
                y = body(xc, lt)
            return (y, g_nxt), xc

        (y, _), xs_saved = jax.lax.scan(
            step, (x, g0), (nxt, fused_stack, repl_stack))
        return y, (xs_saved, layer_shards)

    def _fwd(x, layer_shards):
        y, res = _forward(x, layer_shards)
        return y, res

    def _bwd_impl(res, dy, errs):
        """Shared backward: returns (dx0, dtree, new_errs) — new_errs
        aligned with ``errs`` (per packed group; identity when the group
        is exact/absent)."""
        xs_saved, layer_shards = res
        leaves, tdef, lp = _prep(layer_shards)
        L = leaves[0].shape[0]
        repl_ids = [j for j, e in enumerate(lp.plan) if e is None]
        repl_stack = tuple(leaves[j] for j in repl_ids)
        fused_stack = tuple(leaves[j] for j in lp.fused)

        def layer_vjp(x_i, lt, dx):
            with _scope():
                _, vjp = jax.vjp(lambda xx, pp: body(xx, pp), x_i, lt)
            return vjp(dx)

        if not lp.groups:
            def bstep0(dx, inp):
                x_i, fused_i, repl_i = inp
                lt = _layer_tree(tdef, lp, leaves, {}, fused_i, repl_i)
                dxi, dlt = layer_vjp(x_i, lt, dx)
                return dxi, tuple(jax.tree_util.tree_leaves(dlt))
            dx0, dleaves = jax.lax.scan(
                bstep0, dy, (xs_saved, fused_stack, repl_stack),
                reverse=True)
            dtree = jax.tree_util.tree_unflatten(tdef, list(dleaves))
            return dx0, dtree, errs

        packed_groups = tuple(
            jnp.concatenate([leaves[i].reshape(L, -1) for i in ids], axis=1)
            if len(ids) > 1 else leaves[ids[0]].reshape(L, -1)
            for _, ids in lp.groups)
        gL = _gather_groups(tuple(pg[-1] for pg in packed_groups),
                            axis_name, n, mode, hier=hier)
        # backward iteration i consumes layer i's gathered buffer (in the
        # carry) and prefetches layer i-1's (the NEXT backward step);
        # iteration 0 redundantly re-gathers layer L-1, mirroring forward
        prev = tuple(jnp.roll(pg, 1, axis=0) for pg in packed_groups)
        # per-layer error state rides the scan as xs (each layer owns
        # its slice — no cross-layer dependence, so reverse order is
        # immaterial) and the updated slice comes back as ys
        err_xs = errs if errs is not None else (None,) * len(lp.groups)

        def bstep(carry, inp):
            dx, g_cur = carry
            x_i, prev_bufs, fused_i, repl_i, err_i = inp
            g_prev = _gather_groups(prev_bufs, axis_name, n, mode,
                                    hier=hier)
            full = _unpack_layer_full(g_cur, leaves, lp)
            lt = _layer_tree(tdef, lp, leaves, full, fused_i, repl_i)
            dxi, dlt = layer_vjp(x_i, lt, dx)
            d_leaves = jax.tree_util.tree_leaves(dlt)
            d_by_id = {i: d_leaves[i] for i in lp.sharded_ids}
            # layer i's param-grad reduce-scatter rides the same ring the
            # re-gather of layer i-1 just seeded — both directions busy.
            # Fused leaves are absent here: their reduce-scatter already
            # happened INSIDE the body's matmul+RS kernels (d_leaves[i]
            # is the shard-shaped SUM).
            d_shards, errs_out = _scatter_layer_grads(
                d_by_id, leaves, lp, axis_name, n, mode, hier=hier,
                errs_in=err_i)
            ys = (tuple(d_shards[i] for i in lp.sharded_ids),
                  tuple(d_leaves[i] for i in lp.fused),
                  tuple(d_leaves[j] for j in repl_ids),
                  errs_out)
            return (dxi, g_prev), ys

        (dx0, _), (dshard_stack, dfused_stack, drepl_stack, derr_stack) = \
            jax.lax.scan(
                bstep, (dy, gL),
                (xs_saved, prev, fused_stack, repl_stack, err_xs),
                reverse=True)

        out: List[Any] = [None] * len(leaves)
        for k, i in enumerate(lp.sharded_ids):
            out[i] = dshard_stack[k]
        for k, i in enumerate(lp.fused):
            out[i] = dfused_stack[k]
        for k, j in enumerate(repl_ids):
            out[j] = drepl_stack[k]
        return dx0, jax.tree_util.tree_unflatten(tdef, out), derr_stack

    def _bwd(res, dy):
        dx0, dtree, _ = _bwd_impl(res, dy, None)
        return dx0, dtree

    scan_fn.defvjp(_fwd, _bwd)
    if hier is None:
        return scan_fn

    # hierarchical variant (ISSUE 16): the errs input exists so the
    # backward's compressed slow hops can RETURN their updated error
    # state — the errs "cotangent" is the new per-layer residual, not a
    # derivative (forward never reads errs). The engine threads it into
    # opt_state across steps.
    @jax.custom_vjp
    def scan_fn_h(x, layer_shards, errs):
        y, _ = _forward(x, layer_shards)
        return y

    def _fwd_h(x, layer_shards, errs):
        y, res = _forward(x, layer_shards)
        return y, (res, errs)

    def _bwd_h(res_errs, dy):
        res, errs = res_errs
        return _bwd_impl(res, dy, errs)

    scan_fn_h.defvjp(_fwd_h, _bwd_h)
    return scan_fn_h


# ---------------------------------------------------------------------------
# outer (non-layer) sharded params
# ---------------------------------------------------------------------------

def make_gathered_param(entry, axis_name: str, n: int, mode: str = "ring",
                        hier=None):
    """``g(shard) -> full`` for one non-layer sharded leaf (wte/wpe/...),
    with a custom VJP whose backward reduce-scatters the cotangent (SUM
    over the axis, fp32) instead of relying on transpose rules the
    legacy shard_map lowering lacks. Gathered once per step — these
    leaves are live for the whole step (embedding at the entry, head at
    the exit), like the reference's persistent parameters. ``hier``
    routes both directions through the two-level schedule (exact slow
    hop — see `make_gathered_param_with_error` for the compressed
    one)."""

    @jax.custom_vjp
    def g(shard):
        return gather_leaf(shard, entry, axis_name, n, mode, hier=hier)

    def fwd(shard):
        return g(shard), None

    def bwd(_, cot):
        return (scatter_grad(cot, entry, axis_name, n, mode, hier=hier),)

    g.defvjp(fwd, bwd)
    return g


def make_gathered_param_with_error(entry, axis_name: str, n: int,
                                   mode: str, hier):
    """Compressed-slow-hop variant of `make_gathered_param` (ISSUE 16):
    ``g(shard, err) -> full`` where the backward reduce-scatters the
    cotangent with error-compensated sign bits on the inter-host hop and
    RETURNS the new residual as the ``err`` input's cotangent (the
    state-through-cotangent shape `make_prefetched_scan` uses for the
    per-layer group legs). ``err`` is the persistent per-device
    [`outer_error_numel(entry_shard_numel, hier)`] fp32 state."""
    assert hier is not None

    @jax.custom_vjp
    def g(shard, err):
        return gather_leaf(shard, entry, axis_name, n, mode, hier=hier)

    def fwd(shard, err):
        return g(shard, err), err

    def bwd(err, cot):
        return scatter_grad_with_error(cot, entry, n, err, hier)

    g.defvjp(fwd, bwd)
    return g


def plan_group_errors(stacked_leaves, plan, n: int, fused_ids, hier):
    """Static per-group compressed-slow-hop decision + error-state
    shapes for the hierarchical per-layer grad leg (host-side; engine
    allocation must agree with the traced scan, so this mirrors
    `build_layer_plan`'s dtype grouping exactly). ``stacked_leaves`` are
    the GLOBAL stacked params ([L, ...full dims]); each group's
    per-device per-layer RS payload is its shard elements summed over
    member leaves. Policy: the HierarchyPlan's compression knob, with
    "auto" comparing the fp32 payload against ``min_bucket_bytes`` (the
    `plan_bucket_compression` rule applied to the per-layer RS buffer).
    Returns a list over packed groups: (L, E) error shape, or None for
    groups whose slow hop stays exact."""
    fused = set(fused_ids)
    groups = {}
    for i, (leaf, entry) in enumerate(zip(stacked_leaves, plan)):
        if entry is None or i in fused:
            continue
        groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
    out = []
    for _, ids in groups.items():
        m = sum(int(np.prod(stacked_leaves[i].shape[1:])) // n for i in ids)
        if hier is None:
            out.append(None)
            continue
        compress = hier.compression == "always" or (
            hier.compression == "auto" and m * 4 >= hier.min_bucket_bytes)
        if not compress:
            out.append(None)
        else:
            L = int(stacked_leaves[ids[0]].shape[0])
            out.append((L, overlap_lib.two_level_error_numel(m, hier)))
    return out


def outer_error_numel(shard_numel: int, hier) -> int:
    """Error-state length for one compressed outer leaf's RS leg."""
    return overlap_lib.two_level_error_numel(int(shard_numel), hier)


def outer_compress(shard_numel: int, hier) -> bool:
    """Whether an outer leaf's slow-hop RS compresses under the plan's
    policy (same rule as `plan_group_errors`)."""
    if hier is None:
        return False
    return hier.compression == "always" or (
        hier.compression == "auto"
        and shard_numel * 4 >= hier.min_bucket_bytes)
