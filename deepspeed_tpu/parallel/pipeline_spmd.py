"""SPMD collective pipeline — the TPU-native execution of the reference's
1F1B instruction schedule (deepspeed/runtime/pipe/engine.py:1209 interpreter
+ p2p broadcast groups, pipe/p2p.py:31-55).

Instead of N processes interpreting per-rank instruction lists and
exchanging activations over NCCL p2p, the whole pipeline is ONE jitted SPMD
program: stage-stacked parameters are sharded over the 'pipe' mesh axis, a
`lax.scan` steps the schedule clock, and `lax.ppermute` rotates activations
stage→stage over ICI. Autodiff through the scan gives the backward pipeline
(reverse ppermute) for free — no SendGrad/RecvGrad bookkeeping.

Schedule shape: GPipe-style fill/drain (M microbatches over S stages,
M + S - 1 ticks). The 1F1B memory profile of the reference
(pipe/schedule.py:182) is recovered by remat-ing each stage body: live
activation state is O(mb) per stage instead of O(M·mb).

Terminology map (reference → here):
  SendActivation/RecvActivation → lax.ppermute(out, 'pipe', ring)
  LoadMicroBatch                → jnp.where(stage_idx == 0, microbatch[t], ...)
  ForwardPass                   → stage_fn under scan
  BackwardPass/SendGrad/RecvGrad→ autodiff of the above
  ReduceGrads                   → GSPMD grad psum over 'data' (outside)
  num_pipe_buffers              → 1 live state + remat (see above)
"""

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib


def stack_stage_params(params, num_stages):
    """[L, ...] layer-stacked pytree → [S, L//S, ...] stage-stacked."""
    def reshape(x):
        L = x.shape[0]
        assert L % num_stages == 0, (
            f"layer count {L} not divisible by {num_stages} stages")
        return x.reshape((num_stages, L // num_stages) + x.shape[1:])
    return jax.tree_util.tree_map(reshape, params)


def unstack_stage_params(params):
    """[S, L//S, ...] → [L, ...]."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), params)


def spmd_pipeline(stage_fn: Callable,
                  stage_params,
                  microbatches,
                  mesh,
                  batch_spec: P = None):
    """Run M microbatches through S pipeline stages.

    stage_fn(stage_local_params, x) -> y with y.shape == x.shape; applied by
    every stage to the activation it holds (all layers of that stage).
    stage_params: pytree with leading stage dim S on every leaf.
    microbatches: [M, mb, ...] activations entering stage 0.
    Returns [M, mb, ...] outputs of the last stage (replicated over 'pipe').
    """
    S = mesh.shape[mesh_lib.PIPE_AXIS]
    if S == 1:
        squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return jax.lax.map(lambda x: stage_fn(squeezed, x), microbatches)

    M = microbatches.shape[0]
    perm = [(i, (i + 1) % S) for i in range(S)]
    # shard_map ONLY over the pipe axis: data/seq/model stay in GSPMD "auto"
    # mode, so stage_fn composes with ZeRO/TP shardings untouched.
    if batch_spec is None:
        batch_spec = P()  # replicated w.r.t. pipe; data sharding is auto

    param_specs = jax.tree_util.tree_map(
        lambda x: P(mesh_lib.PIPE_AXIS, *([None] * (x.ndim - 1))), stage_params)

    # boundary activations cross the shard_map edge in f32: the backward of a
    # pipe-replicated bf16 input is a bf16 all-reduce over the manual axis,
    # which crashes XLA-CPU's AllReducePromotion pass. Compute stays in the
    # caller's dtype inside the stages.
    act_dtype = microbatches.dtype

    @functools.partial(
        jax.shard_map, mesh=mesh,
        axis_names=frozenset({mesh_lib.PIPE_AXIS}),
        in_specs=(param_specs, batch_spec),
        out_specs=batch_spec)
    def run(params_local, mb_local):
        # make the replicated microbatch buffer pipe-varying HERE, in f32:
        # pcast's transpose is the psum of the input cotangent over 'pipe',
        # and it must not run in bf16 (see note above)
        mb_local = jax.lax.pcast(
            mb_local, (mesh_lib.PIPE_AXIS,), to="varying").astype(act_dtype)
        local = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        body = jax.checkpoint(lambda x: stage_fn(local, x), prevent_cse=False)

        def tick(state, t):
            # LoadMicroBatch on stage 0; upstream activation elsewhere
            feed = jax.lax.dynamic_index_in_dim(
                mb_local, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
            x = jnp.where(idx == 0, feed, state)
            out = body(x)
            # Send/RecvActivation: rotate one hop around the pipe ring
            nxt = jax.lax.ppermute(out, mesh_lib.PIPE_AXIS, perm)
            return nxt, out

        # pcast's transpose is a psum over 'pipe'; route it through f32
        # (same XLA-CPU bf16 AllReducePromotion crash as the output psum)
        state0 = jax.lax.pcast(
            jnp.zeros(mb_local.shape[1:], jnp.float32),
            (mesh_lib.PIPE_AXIS,), to="varying").astype(act_dtype)
        _, outs = jax.lax.scan(tick, state0, jnp.arange(M + S - 1))
        # last stage's outs at ticks [S-1, S-1+M) are the results; broadcast
        # them to every stage so downstream (loss) code is stage-agnostic.
        # psum in f32: XLA-CPU's AllReducePromotion pass crashes on bf16
        # all-reduce emitted from manual shard_map regions.
        result = jax.lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        masked = jnp.where(idx == S - 1, result,
                           jnp.zeros_like(result)).astype(jnp.float32)
        return jax.lax.psum(masked, mesh_lib.PIPE_AXIS)

    # eager shard_map can't trace closed_call (jax.checkpoint); the engine
    # always calls this under jit — this inner jit covers direct/eager use
    out = jax.jit(run)(stage_params, microbatches.astype(jnp.float32))
    return out.astype(act_dtype)
