"""Cartesian process topology — API-parity rebuild of
deepspeed/runtime/pipe/topology.py:12-455.

On TPU the *communication* side of this file is obsolete — mesh axes replace
process groups (see mesh.py). What survives is the pure coordinate math:
rank ↔ (pipe, data, model) mapping used for checkpoint naming, stage
assignment and grid bookkeeping. `PipelineParallelGrid` keeps the reference's
accessor surface (get_stage_id, get_data_parallel_rank, …) but is backed by a
`jax.sharding.Mesh` when one is supplied.
"""

from collections import namedtuple
from itertools import product


class ProcessTopology:
    """Maps n-dim cartesian coordinates to linear ranks, axes major→minor.

    Mirrors reference pipe/topology.py:12 (ProcessCoord namedtuples, filter
    queries, etc.)."""

    def __init__(self, axes, dims):
        self.axes = axes
        self.dims = dims
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping = {}
        ranges = [range(d) for d in dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = {axis: coord[self.axes.index(axis)] for axis in self.axes}
            key = self.ProcessCoord(**key)
            self.mapping[key] = global_rank

    def get_rank(self, **coord_kwargs):
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() does not support slices, use filter_match")
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"key {coord_kwargs} invalid"
        return self.mapping[key]

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_", outer_sep="-"):
        """String used in checkpoint filenames (reference topology.py:87):
        e.g. mp_rank_00 style naming omits data/pipe axes."""
        omit_axes = list(omit_axes)
        axes = [a for a in self.get_axis_names() if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology.")

    def get_axis_comm_lists(self, axis):
        """All groups of ranks that vary along ``axis`` with other coords
        fixed — the reference built process groups from these lists
        (topology.py:139)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for coord in product(*ranges):
            other_keys = {a: coord[other_axes.index(a)] for a in other_axes}
            sub_list = []
            for axis_key in range(self.get_dim(axis)):
                key = self.ProcessCoord(**other_keys, **{axis: axis_key})
                sub_list.append(self.mapping[key])
            lists.append(sub_list)
        return lists

    def filter_match(self, **filter_kwargs):
        """Ranks whose coords match all kwargs (reference topology.py:167)."""
        def _filter_helper(x):
            for key, val in filter_kwargs.items():
                if getattr(x, key) != val:
                    return False
            return True
        coords = filter(_filter_helper, self.mapping.keys())
        return [self.mapping[coord] for coord in coords]

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


def _prime_factors(N):
    """Prime factorization in increasing order (reference topology.py:230)."""
    if N <= 0:
        raise ValueError("Factorization requires N > 0")
    primes = []
    while N % 2 == 0:
        primes.append(2)
        N //= 2
    p = 3
    while p * p <= N:
        while N % p == 0:
            primes.append(p)
            N //= p
        p += 2
    if N > 1:
        primes.append(N)
    return primes


class PipeDataParallelTopology(ProcessTopology):
    """Hybrid pipeline+data topology; DP innermost for intra-node allreduce
    bandwidth (reference topology.py:235)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D topology for DP×PP×TP (reference topology.py:246)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Rank-bookkeeping for a hybrid grid — reference topology.py:252-455.

    The reference builds torch process groups here; on TPU the mesh axes carry
    the collectives, so this class only answers "who am I" queries. ``mesh``
    (optional) ties it to a real jax Mesh; ``process_id`` selects this
    process's coordinates (defaults to jax.process_index for multi-host)."""

    def __init__(self, topology=None, process_group=None, mesh=None,
                 world_size=None, global_rank=0):
        if topology is None:
            if mesh is not None:
                num_pp = mesh.shape.get("pipe", 1)
                num_mp = mesh.shape.get("model", 1)
                num_dp = (mesh.size // (num_pp * num_mp))
                topology = PipeModelDataParallelTopology(num_pp=num_pp,
                                                         num_mp=num_mp,
                                                         num_dp=num_dp)
            else:
                ws = world_size or 1
                topology = PipeDataParallelTopology(num_pp=1, num_dp=ws)
        self._topo = topology
        self.mesh = mesh
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(self._topo.get_dim("data"), 1)
        self.pipe_parallel_size = max(self._topo.get_dim("pipe"), 1)
        self.model_parallel_size = max(self._topo.get_dim("model"), 1)
        assert self.world_size == (self.data_parallel_size * self.pipe_parallel_size
                                   * self.model_parallel_size)

        self.stage_id = self.get_stage_id()
        self.data_parallel_id = self.get_data_parallel_id()

        # p2p pair lists kept for schedule bookkeeping (reference
        # _build_p2p_groups topology.py:373); on TPU these become ppermute
        # source/dest index pairs over the pipe axis.
        self.p2p_matrix = self._build_p2p_pairs()

    def _build_p2p_pairs(self):
        pairs = []
        if self.pipe_parallel_size <= 1:
            return pairs
        for rank in range(self.world_size):
            coord = self._topo.get_coord(rank)
            stage = getattr(coord, "pipe", 0)
            next_stage = (stage + 1) % self.pipe_parallel_size
            kwargs = coord._asdict()
            kwargs["pipe"] = next_stage
            pairs.append((rank, self._topo.get_rank(**kwargs)))
        return pairs

    def get_stage_id(self):
        if "pipe" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "pipe", 0)

    def get_data_parallel_id(self):
        if "data" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "data", 0)

    # -- reference accessor surface (topology.py:395-455) ------------------
    def get_global_rank(self):
        return self.global_rank

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_rank(self):
        return self.get_data_parallel_id()

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_rank(self):
        if "model" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "model", 0)

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    # mesh-era group accessors: return the axis name to use in collectives
    def get_pipe_parallel_group(self):
        return "pipe"

    def get_data_parallel_group(self):
        return "data"

    def get_model_parallel_group(self):
        return "model"

    def get_slice_parallel_group(self):
        # alias of model group, as in reference topology.py:455
        return "model"

    def topology(self):
        return self._topo

    def stage_to_global(self, stage_id, **kwargs):
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1
