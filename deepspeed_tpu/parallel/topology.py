"""Cartesian process topology — API-parity rebuild of
deepspeed/runtime/pipe/topology.py:12-455.

On TPU the *communication* side of this file is obsolete — mesh axes replace
process groups (see mesh.py). What survives is the pure coordinate math:
rank ↔ (pipe, data, model) mapping used for checkpoint naming, stage
assignment and grid bookkeeping. `PipelineParallelGrid` keeps the reference's
accessor surface (get_stage_id, get_data_parallel_rank, …) but is backed by a
`jax.sharding.Mesh` when one is supplied.
"""

import dataclasses
import itertools
from collections import namedtuple

import numpy as np


class ProcessTopology:
    """Maps n-dim cartesian coordinates to linear ranks, axes major→minor.

    API parity with reference pipe/topology.py:12, but backed by a numpy
    rank grid the way `jax.sharding.Mesh` is backed by a devices ndarray:
    a coordinate lookup is an array index, a comm list is an axis slice,
    and a filter query is fancy indexing — no dict scans."""

    def __init__(self, axes, dims):
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        # C-order reshape gives the odometer rank numbering (last axis
        # fastest) that the reference's coordinate enumeration produced.
        self._grid = np.arange(int(np.prod(self.dims))).reshape(self.dims)

    def get_rank(self, **coord_kwargs):
        if set(coord_kwargs) != set(self.axes):
            raise ValueError("get_rank() does not support slices, use filter_match")
        idx = tuple(coord_kwargs[a] for a in self.axes)
        if any(not 0 <= i < d for i, d in zip(idx, self.dims)):
            raise AssertionError(f"key {coord_kwargs} invalid")
        return int(self._grid[idx])

    def get_axis_names(self):
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_", outer_sep="-"):
        """String used in checkpoint filenames (reference topology.py:87):
        e.g. mp_rank_00 style naming omits data/pipe axes."""
        coord = self.get_coord(rank)._asdict()
        return outer_sep.join(
            f"{ax}{inner_sep}{coord[ax]:02d}"
            for ax in self.axes if ax not in omit_axes)

    def get_dim(self, axis):
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank):
        if not 0 <= rank < self._grid.size:
            raise ValueError(f"rank {rank} not found in topology.")
        return self.ProcessCoord(*map(int, np.unravel_index(rank, self.dims)))

    def get_axis_comm_lists(self, axis):
        """All groups of ranks that vary along ``axis`` with other coords
        fixed — the reference built process groups from these lists
        (topology.py:139). Here: move ``axis`` last and flatten the rest,
        so each row of the resulting matrix is one comm group."""
        if axis not in self.axes:
            return []
        rows = np.moveaxis(self._grid, self.axes.index(axis), -1)
        return rows.reshape(-1, self.get_dim(axis)).tolist()

    def filter_match(self, **filter_kwargs):
        """Ranks whose coords match all kwargs (reference topology.py:167),
        as a sorted list: index the grid with the fixed coordinates and
        flatten whatever remains. Unknown axis names raise (the dict-based
        original raised AttributeError); out-of-range values match nothing."""
        for axis, val in filter_kwargs.items():
            if axis not in self.axes:
                raise AttributeError(f"unknown topology axis {axis!r}; "
                                     f"have {self.axes}")
            if not 0 <= val < self.get_dim(axis):
                return []
        selector = tuple(
            filter_kwargs.get(a, slice(None)) for a in self.axes)
        return np.atleast_1d(self._grid[selector]).ravel().tolist()

    def get_axis_list(self, axis, idx):
        return self.filter_match(**{axis: idx})

    def world_size(self):
        return int(self._grid.size)

    @property
    def mapping(self):
        """coord→rank dict view (kept for repr/debug parity)."""
        return {self.get_coord(r): r for r in range(self.world_size())}

    def __str__(self):
        return str(self.mapping)


@dataclasses.dataclass(frozen=True)
class DataAxisHierarchy:
    """A two-level split of the mesh data axis for link-aware comm
    (ISSUE 10): ``inter`` slow-link groups (DCN-class hops between
    hosts/processes) of ``intra`` fast-link devices each (ICI-class hops
    inside a host). ``source`` records how the split was derived —
    ``"process"`` (real jax.distributed process boundaries) or
    ``"override"`` (the ``comm.hierarchy.slow_axis`` synthetic split for
    single-process testing)."""
    inter: int
    intra: int
    source: str


def data_axis_devices(mesh, data_axis="data"):
    """The device sequence along ``data_axis`` (other coordinates fixed
    at 0), in mesh order — the ordering the hierarchy split and the
    explicit ring programs both walk."""
    if data_axis not in mesh.axis_names:
        return []
    devs = np.moveaxis(mesh.devices,
                       list(mesh.axis_names).index(data_axis), 0)
    return list(devs.reshape(devs.shape[0], -1)[:, 0])


def derive_data_hierarchy(mesh, slow_axis=0, data_axis="data"):
    """Resolve the slow/fast split of ``mesh``'s data axis.

    ``slow_axis > 1`` forces a synthetic split into that many slow-link
    groups (single-process testing of the multi-host exchange — the
    config override); ``slow_axis`` 0 derives the split from the REAL
    process boundaries: the devices along the data axis must form
    contiguous, equal-sized, per-process blocks (what
    ``jax.distributed.initialize`` + a host-major mesh produce).

    Returns ``(DataAxisHierarchy, "")`` on success or ``(None, reason)``
    when no slow axis exists / the placement cannot be split — callers
    fall back loudly to the flat exchange."""
    n = mesh.shape.get(data_axis, 1) if hasattr(mesh, "shape") else 1
    if n <= 1:
        return None, f"data axis has size {n} (nothing to split)"
    if slow_axis and int(slow_axis) > 1:
        s = int(slow_axis)
        if n % s != 0:
            return None, (f"slow_axis override {s} does not divide the "
                          f"data axis size {n}")
        return DataAxisHierarchy(inter=s, intra=n // s,
                                 source="override"), ""
    procs = [getattr(d, "process_index", 0)
             for d in data_axis_devices(mesh, data_axis)]
    blocks = [(p, len(list(g))) for p, g in itertools.groupby(procs)]
    if len(blocks) <= 1:
        return None, ("single process on the data axis — no slow links "
                      "(set comm.hierarchy.slow_axis for a synthetic "
                      "split)")
    if len({p for p, _ in blocks}) != len(blocks):
        return None, ("process placement along the data axis is not "
                      "contiguous (a process's devices interleave with "
                      "another's)")
    if len({ln for _, ln in blocks}) != 1:
        return None, "uneven devices-per-process along the data axis"
    return DataAxisHierarchy(inter=len(blocks), intra=blocks[0][1],
                             source="process"), ""


# flat-fallback warning latch (ISSUE 16 satellite): callers of
# ``derive_data_hierarchy`` warn + drop a ``comm_hierarchy_fallback``
# breadcrumb when the split fails, and a caller that re-derives per
# step-build would flood the bounded flight-recorder ring with the same
# event. Latched process-wide per (axis, reason) — same shape as the
# router_block episode latch from the serving router.
_FALLBACK_LATCH = set()


def latch_fallback(axis, reason):
    """True exactly once per distinct (axis, reason) fallback; False on
    repeats. Callers gate their warning + breadcrumb on this."""
    key = (str(axis), str(reason))
    if key in _FALLBACK_LATCH:
        return False
    _FALLBACK_LATCH.add(key)
    return True


def reset_fallback_latch():
    """Test hook: forget latched fallbacks (process-wide state)."""
    _FALLBACK_LATCH.clear()


def _prime_factors(N):
    """Prime factorization in increasing order (reference topology.py:230)."""
    if N <= 0:
        raise ValueError("Factorization requires N > 0")
    primes = []
    while N % 2 == 0:
        primes.append(2)
        N //= 2
    p = 3
    while p * p <= N:
        while N % p == 0:
            primes.append(p)
            N //= p
        p += 2
    if N > 1:
        primes.append(N)
    return primes


class PipeDataParallelTopology(ProcessTopology):
    """Hybrid pipeline+data topology; DP innermost for intra-node allreduce
    bandwidth (reference topology.py:235)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D topology for DP×PP×TP (reference topology.py:246)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Rank-bookkeeping for a hybrid grid — reference topology.py:252-455.

    The reference builds torch process groups here; on TPU the mesh axes carry
    the collectives, so this class only answers "who am I" queries. ``mesh``
    (optional) ties it to a real jax Mesh; ``process_id`` selects this
    process's coordinates (defaults to jax.process_index for multi-host)."""

    def __init__(self, topology=None, process_group=None, mesh=None,
                 world_size=None, global_rank=0):
        if topology is None:
            if mesh is not None:
                num_pp = mesh.shape.get("pipe", 1)
                num_mp = mesh.shape.get("model", 1)
                num_dp = (mesh.size // (num_pp * num_mp))
                topology = PipeModelDataParallelTopology(num_pp=num_pp,
                                                         num_mp=num_mp,
                                                         num_dp=num_dp)
            else:
                ws = world_size or 1
                topology = PipeDataParallelTopology(num_pp=1, num_dp=ws)
        self._topo = topology
        self.mesh = mesh
        self.global_rank = global_rank
        self.world_size = topology.world_size()

        self.data_parallel_size = max(self._topo.get_dim("data"), 1)
        self.pipe_parallel_size = max(self._topo.get_dim("pipe"), 1)
        self.model_parallel_size = max(self._topo.get_dim("model"), 1)
        assert self.world_size == (self.data_parallel_size * self.pipe_parallel_size
                                   * self.model_parallel_size)

        self.stage_id = self.get_stage_id()
        self.data_parallel_id = self.get_data_parallel_id()

        # p2p pair lists kept for schedule bookkeeping (reference
        # _build_p2p_groups topology.py:373); on TPU these become ppermute
        # source/dest index pairs over the pipe axis.
        self.p2p_matrix = self._build_p2p_pairs()

    def _build_p2p_pairs(self):
        pairs = []
        if self.pipe_parallel_size <= 1:
            return pairs
        for rank in range(self.world_size):
            coord = self._topo.get_coord(rank)
            stage = getattr(coord, "pipe", 0)
            next_stage = (stage + 1) % self.pipe_parallel_size
            kwargs = coord._asdict()
            kwargs["pipe"] = next_stage
            pairs.append((rank, self._topo.get_rank(**kwargs)))
        return pairs

    def get_stage_id(self):
        if "pipe" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "pipe", 0)

    def get_data_parallel_id(self):
        if "data" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "data", 0)

    # -- reference accessor surface (topology.py:395-455) ------------------
    def get_global_rank(self):
        return self.global_rank

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_rank(self):
        return self.get_data_parallel_id()

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_rank(self):
        if "model" not in self._topo.get_axis_names():
            return 0
        return getattr(self._topo.get_coord(rank=self.global_rank), "model", 0)

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    # mesh-era group accessors: return the axis name to use in collectives
    def get_pipe_parallel_group(self):
        return "pipe"

    def get_data_parallel_group(self):
        return "data"

    def get_model_parallel_group(self):
        return "model"

    def get_slice_parallel_group(self):
        # alias of model group, as in reference topology.py:455
        return "model"

    def topology(self):
        return self._topo

    def stage_to_global(self, stage_id, **kwargs):
        me = self._topo.get_coord(self.global_rank)
        transform = me._replace(pipe=stage_id, **kwargs)._asdict()
        return self._topo.get_rank(**transform)

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1
