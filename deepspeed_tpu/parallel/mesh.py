"""Device mesh construction — the TPU-native communication substrate.

Replaces the reference's process-group machinery (torch.distributed/NCCL init
in deepspeed/utils/distributed.py:12-142 and the group building in
deepspeed/runtime/pipe/topology.py:252-455). On TPU every collective is an
axis-scoped XLA op over a `jax.sharding.Mesh`; "creating a process group"
becomes naming a mesh axis.

Canonical axis order (outer→inner): ``('pipe', 'data', 'seq', 'model')`` —
pipe outermost so stages land on contiguous sub-slices (cheap DCN hops between
stages, fat ICI inside a stage for data/model collectives), matching the
reference's topology axis order ['pipe','data','model']
(pipe/topology.py:246).
"""

import dataclasses
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

# Mesh axis names. ZeRO shards over DATA_AXIS; tensor parallelism over
# MODEL_AXIS; pipeline stages over PIPE_AXIS; ring-attention/sequence
# parallelism over SEQ_AXIS; MoE experts over EXPERT_AXIS (a dedicated
# axis when MeshConfig.expert > 1, else experts alias onto data).
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"

AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)

_current_mesh: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]):
    """Engine-scoped mesh registry: model code (e.g. ring attention inside
    SelfAttention) can discover the active mesh without threading it through
    flax module attributes."""
    global _current_mesh
    _current_mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


# pin scopes are PER-THREAD: two engines tracing concurrently from
# different threads must not cross-contaminate each other's pin state
# (the registries above stay process-global by design — a mesh is not
# thread-scoped, a trace is)
import threading

_pin_state = threading.local()


def _pins_disabled_count():
    return getattr(_pin_state, "disabled", 0)


def _get_pin_mesh():
    return getattr(_pin_state, "mesh", None)


class layout_pins:
    """Engine-scoped activation of the models' GSPMD layout pins
    (with_sharding_constraint on param/grad edges, e.g. the wpe slice and
    wte-scatter pins in models/gpt2.py). The pins must NOT read the
    ambient mesh registry: set_current_mesh outlives its engine, and a
    later single-device jit tracing the model with a constraint over a
    stale multi-device mesh crashes XLA's CPU compiler (the r4
    full-suite Fatal abort — order-dependent, invisible in isolation).
    Engines enter this around every jitted call with THEIR mesh; any
    trace outside an engine gets no pins. Re-entrant; inner-most wins."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._prev = None

    def __enter__(self):
        self._prev = _get_pin_mesh()
        _pin_state.mesh = self.mesh
        return self

    def __exit__(self, *exc):
        _pin_state.mesh = self._prev
        return False


def pinned_mesh():
    """Mesh for model layout pins, or None outside an engine-pinned
    trace (or when pins are disabled for explicit-comm programs)."""
    if _pins_disabled_count() > 0:
        return None
    return _get_pin_mesh()


class no_layout_pins:
    """Context manager disabling the models' GSPMD layout pins
    (with_sharding_constraint on param/grad edges) while an engine traces
    an EXPLICIT-COMM program (shard_map, Manual axes). Inside shard_map
    the data is already device-local, so the pins are meaningless — and a
    NamedSharding built over the global (Auto-axis) mesh poisons avals in
    ways trace-context sniffing cannot reliably detect: custom_vjp
    backwards re-trace under whatever mesh context is live at transpose
    time (sometimes empty, sometimes the Auto mesh), so the ENGINE —
    which knows which kind of program it is building — is the only
    authoritative source. Re-entrant."""

    def __enter__(self):
        _pin_state.disabled = _pins_disabled_count() + 1
        return self

    def __exit__(self, *exc):
        _pin_state.disabled = _pins_disabled_count() - 1
        return False


def layout_pins_disabled() -> bool:
    return _pins_disabled_count() > 0


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True):
    """Multi-host initialization — parity with reference
    deepspeed/utils/distributed.py:12. Full resolution order (launcher env
    contract, generic env, MPI discovery) lives in utils/distributed.py;
    single-process is a no-op."""
    from deepspeed_tpu.utils.distributed import init_distributed as _init
    _init(coordinator_address=coordinator_address,
          num_processes=num_processes,
          process_id=process_id,
          auto_mpi_discovery=auto_mpi_discovery)


@dataclasses.dataclass
class MeshConfig:
    """Logical parallelism degrees. ``data=-1`` absorbs the remaining devices.

    The product pipe*data*seq*model must equal the device count (after -1
    resolution)."""
    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        explicit = self.model * self.pipe * self.seq * self.expert
        data = self.data
        if data == -1:
            assert n_devices % explicit == 0, (
                f"device count {n_devices} not divisible by "
                f"pipe*expert*seq*model={explicit}")
            data = n_devices // explicit
        total = data * explicit
        assert total == n_devices, (
            f"mesh {self.pipe}x{data}x{self.expert}x{self.seq}x"
            f"{self.model} != {n_devices} devices")
        return MeshConfig(data=data, model=self.model, pipe=self.pipe,
                          seq=self.seq, expert=self.expert)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              axis_order: Sequence[str] = AXIS_ORDER) -> Mesh:
    """Build the global device mesh.

    Prefers ``jax.experimental.mesh_utils.create_device_mesh`` so the logical
    mesh lines up with the physical ICI torus; falls back to a plain reshape
    for CPU meshes used in tests.
    """
    if devices is None:
        devices = jax.devices()
    config = (config or MeshConfig()).resolve(len(devices))
    shape = tuple({
        PIPE_AXIS: config.pipe,
        DATA_AXIS: config.data,
        EXPERT_AXIS: config.expert,
        SEQ_AXIS: config.seq,
        MODEL_AXIS: config.model,
    }[a] for a in axis_order)
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axis_order))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(
        (1,) * len(AXIS_ORDER)), AXIS_ORDER)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.axis_names else 1


def dp_world_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh_axis_size(mesh, DATA_AXIS) * mesh_axis_size(mesh, EXPERT_AXIS)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input batches shard dim 0 over (data, expert) — dp_world_size counts
    both, so a dedicated expert axis carries its share of the batch instead
    of replicating non-MoE compute — and dim 1 over the seq axis when one
    exists."""
    dim0 = (DATA_AXIS, EXPERT_AXIS) \
        if mesh_axis_size(mesh, EXPERT_AXIS) > 1 else DATA_AXIS
    if mesh_axis_size(mesh, SEQ_AXIS) > 1:
        return NamedSharding(mesh, PartitionSpec(dim0, SEQ_AXIS))
    return NamedSharding(mesh, PartitionSpec(dim0))
