"""Device mesh construction — the TPU-native communication substrate.

Replaces the reference's process-group machinery (torch.distributed/NCCL init
in deepspeed/utils/distributed.py:12-142 and the group building in
deepspeed/runtime/pipe/topology.py:252-455). On TPU every collective is an
axis-scoped XLA op over a `jax.sharding.Mesh`; "creating a process group"
becomes naming a mesh axis.

Canonical axis order (outer→inner): ``('pipe', 'data', 'seq', 'model')`` —
pipe outermost so stages land on contiguous sub-slices (cheap DCN hops between
stages, fat ICI inside a stage for data/model collectives), matching the
reference's topology axis order ['pipe','data','model']
(pipe/topology.py:246).
"""

import dataclasses
import functools
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

# Mesh axis names. ZeRO shards over DATA_AXIS; tensor parallelism over
# MODEL_AXIS; pipeline stages over PIPE_AXIS; ring-attention/sequence
# parallelism over SEQ_AXIS; MoE experts over EXPERT_AXIS (a dedicated
# axis when MeshConfig.expert > 1, else experts alias onto data).
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"

AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, EXPERT_AXIS, SEQ_AXIS, MODEL_AXIS)

# The hierarchical comm split (ISSUE 10): the data axis factored at the
# host/process boundary into a slow DCN-class outer axis and a fast
# ICI-class inner axis. Only the explicit-comm train programs see these
# names (split_data_axis below); state at rest stays on DATA_AXIS.
DATA_INTER_AXIS = "data_inter"
DATA_INTRA_AXIS = "data_intra"


# ---------------------------------------------------------------------------
# shard_map compat shim
#
# Every explicit-comm program in this repo targets the modern `jax.shard_map`
# API (top-level export, `axis_names=` manual subset, `check_vma=`). The
# pinned jax (0.4.37) only has `jax.experimental.shard_map.shard_map` with the
# older (check_rep, auto) signature, and two of the new API's features do not
# exist there at all:
#
#   * partial-manual (`axis_names` a strict subset of the mesh axes) — the
#     old `auto=` parameter is NotImplemented in eager mode and crashes XLA's
#     SPMD partitioner under jit (IsManualSubgroup check failure), so the
#     shim lowers `axis_names` to FULL-manual: axes the body does not name
#     are simply absent from every spec, which replicates inputs over them at
#     entry. Numerically identical (the bodies only ever bind the named
#     axis); the cost is an entry gather when an input was sharded over an
#     unnamed axis.
#   * the VMA (varying-manual-axes) system — `check_vma` maps onto
#     `check_rep`, and `pvary` (below) becomes a no-op. The old rep-checker
#     predates VMA and rejects valid ppermute/cond carries, so the shim
#     defaults it OFF unless explicitly requested via check_rep=True.
#
# All in-repo call sites import `shard_map`/`pvary` from here instead of
# touching `jax.shard_map` / `jax.lax.pvary` directly.
# ---------------------------------------------------------------------------

#: True when the shim below lowers `axis_names` to FULL-manual (legacy
#: jax). Callers that name secondary mesh axes in their specs to avoid the
#: entry replication (see passthrough_axis) must only do so here — on
#: modern jax the unnamed axes stay auto (partial-manual), specs may not
#: mention them, and there is no replication to avoid.
FULL_MANUAL_LOWERING = not hasattr(jax, "shard_map")

if not FULL_MANUAL_LOWERING:         # modern jax: pass straight through
    shard_map = jax.shard_map

    def pvary(x, axis_names):
        return jax.lax.pvary(x, tuple(axis_names))
else:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f=None, *, mesh, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None,
                  auto=None):
        """Modern `jax.shard_map` surface on the legacy experimental API.

        ``axis_names``/``auto`` are accepted for source compatibility but the
        lowering is always full-manual (see module comment); ``check_vma``
        aliases ``check_rep`` and both default to False."""
        if f is None:
            return functools.partial(
                shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                axis_names=axis_names, check_vma=check_vma,
                check_rep=check_rep, auto=auto)
        check = check_rep if check_rep is not None else check_vma
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs,
                                 check_rep=bool(check) if check is not None
                                 else False)

    def pvary(x, axis_names):
        """No-op on pre-VMA jax: with check_rep off there is no varying/
        unvarying distinction to annotate."""
        return x


def passthrough_axis(mesh, axis: str, dim_size: int):
    """``axis`` if the FULL-manual lowering is active and the axis exists in
    ``mesh``, is live (>1), and divides ``dim_size`` — for naming secondary
    axes in shard_map specs so their tiles pass through manually instead of
    replicating at entry (the full-manual lowering's cost, see the shim
    comment). None otherwise — in particular always None on modern jax,
    where unnamed axes stay auto (no replication) and specs may only
    mention axes in ``axis_names``."""
    if not FULL_MANUAL_LOWERING:
        return None
    n = mesh.shape.get(axis, 1) if hasattr(mesh, "shape") else 1
    if n > 1 and dim_size % n == 0:
        return axis
    return None


def axis_size(axis_name) -> int:
    """Static size of a bound collective axis — `jax.lax.axis_size` compat
    (that API landed after the pinned 0.4.37). On legacy jax, psum of a
    Python literal constant-folds to the axis size at trace time."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def in_manual_region() -> bool:
    """True when the current trace sits inside an explicit-comm region
    (shard_map Manual axes, or any bound collective axis on jax without
    abstract-mesh introspection). Model layout pins must not apply there —
    the data is already device-local."""
    try:
        from jax.sharding import get_abstract_mesh, AxisType
        am = get_abstract_mesh()
        if any(t == AxisType.Manual for t in getattr(am, "axis_types", ())):
            return True
    except ImportError:
        pass
    try:
        from jax._src.core import get_axis_env
        return bool(get_axis_env().axis_sizes)
    except Exception:
        return False


_current_mesh: Optional[Mesh] = None


def set_current_mesh(mesh: Optional[Mesh]):
    """Engine-scoped mesh registry: model code (e.g. ring attention inside
    SelfAttention) can discover the active mesh without threading it through
    flax module attributes."""
    global _current_mesh
    _current_mesh = mesh


def current_mesh() -> Optional[Mesh]:
    return _current_mesh


# pin scopes are PER-THREAD: two engines tracing concurrently from
# different threads must not cross-contaminate each other's pin state
# (the registries above stay process-global by design — a mesh is not
# thread-scoped, a trace is)
import threading

_pin_state = threading.local()


def _pins_disabled_count():
    return getattr(_pin_state, "disabled", 0)


def _get_pin_mesh():
    return getattr(_pin_state, "mesh", None)


class layout_pins:
    """Engine-scoped activation of the models' GSPMD layout pins
    (with_sharding_constraint on param/grad edges, e.g. the wpe slice and
    wte-scatter pins in models/gpt2.py). The pins must NOT read the
    ambient mesh registry: set_current_mesh outlives its engine, and a
    later single-device jit tracing the model with a constraint over a
    stale multi-device mesh crashes XLA's CPU compiler (the r4
    full-suite Fatal abort — order-dependent, invisible in isolation).
    Engines enter this around every jitted call with THEIR mesh; any
    trace outside an engine gets no pins. Re-entrant; inner-most wins."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._prev = None

    def __enter__(self):
        self._prev = _get_pin_mesh()
        _pin_state.mesh = self.mesh
        return self

    def __exit__(self, *exc):
        _pin_state.mesh = self._prev
        return False


def pinned_mesh():
    """Mesh for model layout pins, or None outside an engine-pinned
    trace (or when pins are disabled for explicit-comm programs)."""
    if _pins_disabled_count() > 0:
        return None
    return _get_pin_mesh()


class no_layout_pins:
    """Context manager disabling the models' GSPMD layout pins
    (with_sharding_constraint on param/grad edges) while an engine traces
    an EXPLICIT-COMM program (shard_map, Manual axes). Inside shard_map
    the data is already device-local, so the pins are meaningless — and a
    NamedSharding built over the global (Auto-axis) mesh poisons avals in
    ways trace-context sniffing cannot reliably detect: custom_vjp
    backwards re-trace under whatever mesh context is live at transpose
    time (sometimes empty, sometimes the Auto mesh), so the ENGINE —
    which knows which kind of program it is building — is the only
    authoritative source. Re-entrant."""

    def __enter__(self):
        _pin_state.disabled = _pins_disabled_count() + 1
        return self

    def __exit__(self, *exc):
        _pin_state.disabled = _pins_disabled_count() - 1
        return False


def layout_pins_disabled() -> bool:
    return _pins_disabled_count() > 0


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True):
    """Multi-host initialization — parity with reference
    deepspeed/utils/distributed.py:12. Full resolution order (launcher env
    contract, generic env, MPI discovery) lives in utils/distributed.py;
    single-process is a no-op."""
    from deepspeed_tpu.utils.distributed import init_distributed as _init
    _init(coordinator_address=coordinator_address,
          num_processes=num_processes,
          process_id=process_id,
          auto_mpi_discovery=auto_mpi_discovery)


@dataclasses.dataclass
class MeshConfig:
    """Logical parallelism degrees. ``data=-1`` absorbs the remaining devices.

    The product pipe*data*seq*model must equal the device count (after -1
    resolution)."""
    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        explicit = self.model * self.pipe * self.seq * self.expert
        data = self.data
        if data == -1:
            assert n_devices % explicit == 0, (
                f"device count {n_devices} not divisible by "
                f"pipe*expert*seq*model={explicit}")
            data = n_devices // explicit
        total = data * explicit
        assert total == n_devices, (
            f"mesh {self.pipe}x{data}x{self.expert}x{self.seq}x"
            f"{self.model} != {n_devices} devices")
        return MeshConfig(data=data, model=self.model, pipe=self.pipe,
                          seq=self.seq, expert=self.expert)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None,
              axis_order: Sequence[str] = AXIS_ORDER) -> Mesh:
    """Build the global device mesh.

    Prefers ``jax.experimental.mesh_utils.create_device_mesh`` so the logical
    mesh lines up with the physical ICI torus; falls back to a plain reshape
    for CPU meshes used in tests.
    """
    if devices is None:
        devices = jax.devices()
    config = (config or MeshConfig()).resolve(len(devices))
    shape = tuple({
        PIPE_AXIS: config.pipe,
        DATA_AXIS: config.data,
        EXPERT_AXIS: config.expert,
        SEQ_AXIS: config.seq,
        MODEL_AXIS: config.model,
    }[a] for a in axis_order)
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)  # sync-ok: host device list
    return Mesh(dev_array, axis_names=tuple(axis_order))


def split_data_axis(mesh: Mesh, inter: int) -> Mesh:
    """Mesh with the data axis factored into ``(data_inter, data_intra)``
    — same devices in the same order (row-major split, so the ``intra``
    fast-axis neighbors are the devices that were contiguous along the
    original data axis: one host's local devices when the data axis is
    laid out host-major). Resharding an array between the two meshes is
    metadata-only — no device ever changes which elements it holds."""
    names = list(mesh.axis_names)
    di = names.index(DATA_AXIS)
    n = mesh.devices.shape[di]
    assert inter > 0 and n % inter == 0, (
        f"data axis {n} not divisible by inter={inter}")
    shape = list(mesh.devices.shape)
    shape[di:di + 1] = [inter, n // inter]
    names[di:di + 1] = [DATA_INTER_AXIS, DATA_INTRA_AXIS]
    return Mesh(mesh.devices.reshape(shape), tuple(names))


def linear_axis_index(axis):
    """`jax.lax.axis_index` linearized over one bound axis name or an
    (outer, ..., inner) tuple — the device's odometer rank over the named
    axes (the pinned 0.4.37 axis_index takes a single name only)."""
    if isinstance(axis, (tuple, list)):
        idx = jax.lax.axis_index(axis[0])
        for a in axis[1:]:
            idx = idx * axis_size(a) + jax.lax.axis_index(a)
        return idx
    return jax.lax.axis_index(axis)


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(  # sync-ok: host device list
        (1,) * len(AXIS_ORDER)), AXIS_ORDER)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.axis_names else 1


def dp_world_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return mesh_axis_size(mesh, DATA_AXIS) * mesh_axis_size(mesh, EXPERT_AXIS)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input batches shard dim 0 over (data, expert) — dp_world_size counts
    both, so a dedicated expert axis carries its share of the batch instead
    of replicating non-MoE compute — and dim 1 over the seq axis when one
    exists."""
    dim0 = (DATA_AXIS, EXPERT_AXIS) \
        if mesh_axis_size(mesh, EXPERT_AXIS) > 1 else DATA_AXIS
    if mesh_axis_size(mesh, SEQ_AXIS) > 1:
        return NamedSharding(mesh, PartitionSpec(dim0, SEQ_AXIS))
    return NamedSharding(mesh, PartitionSpec(dim0))
