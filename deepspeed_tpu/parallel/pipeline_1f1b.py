"""1F1B SPMD pipeline executor — the TPU-native execution of the reference's
TrainSchedule (deepspeed/runtime/pipe/schedule.py:182, engine interpreter
pipe/engine.py:1209).

The reference runs N processes, each interpreting a per-rank instruction
list and exchanging tensors over NCCL p2p. Here the whole pipeline is ONE
SPMD program under `jax.custom_vjp`:

- **forward** (`_forward_program`): GPipe fill/drain over M + S - 1 ticks;
  each tick applies the stage body and rotates activations one hop around
  the 'pipe' mesh axis with `lax.ppermute`. Nothing is saved for backward
  beyond (params, inputs) — O(1) activation memory.
- **backward**: a hand-written replay. Two tick programs:

  * **interleaved** (`interleave=True`) — the reference's even/odd 1F1B
    schedule over 2·(M + S - 1) ticks. The tick → (micro_batch, fwd|bwd)
    mapping is the closed form of `TrainSchedule._step_to_micro_batch`
    (schedule.py:220-251):

        is_fwd(t, s)  =  t ≡ s (mod 2)
        fwd µbatch    =  t//2 - s//2          (fwd(m) at t = 2m + s)
        bwd µbatch    =  t//2 - S + 1 + s//2  (bwd(m) at t = 2m + 2S - 1 - s)

    (`tests/test_pipeline_1f1b.py` asserts this closed form agrees with
    the TrainSchedule instruction stream tick-for-tick, so schedule.py is
    the executable contract, not documentation.) Each stage keeps a
    rotating buffer of its stage inputs with `num_pipe_buffers =
    min(S + 1, M)` slots — the reference's memory bound
    (schedule.py:243-247). **Constraint:** fwd/bwd ticks run in `lax.cond`
    branches selected per stage, so the stage body must not contain
    cross-device collectives — with TP/ZeRO axes active, GSPMD would place
    model/data-axis collectives inside diverging branches and the devices
    deadlock (a fundamental SPMD-pipelining constraint, not an
    implementation detail).

  * **uniform** (`interleave=False`) — fill/drain forward then drain
    backward, every device executing the identical op sequence every tick
    (invalid ticks compute on zeros and mask their writes). Auto-axis
    collectives from ZeRO/TP/SP inside the stage body stay aligned across
    devices, so this variant composes with any mesh. Same tick count and
    bubble as the interleaved schedule — 1F1B's advantage is memory, not
    bubble — but the stage-input buffer is O(M) instead of O(S).

  Default: interleaved exactly when the mesh has no non-trivial axis other
  than 'pipe'.

  A backward tick recomputes the stage forward under `jax.vjp` from the
  buffered input (rematerialization — the TPU analog of the reference's
  activation checkpointing default) and sends the input-cotangent one hop
  backwards.

Because both programs are forward-only as far as JAX autodiff is concerned
(the custom VJP *is* the backward), no collective inside them is ever
transposed — which removes the f32 upcast workarounds the autodiff GPipe
path needed around XLA-CPU's bf16 all-reduce promotion (kept only for the
two explicit result psums, gated to non-TPU backends).

Compute cost: fwd + (fwd + vjp) ≈ one extra forward per step — identical
to full-remat GPipe (what the engine paid before), but live activations
drop from O(M + S) microbatch buffers plus scan residuals to the
stage-input buffer above.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.utils.platform import is_tpu_backend


def stack_stage_params(params, num_stages):
    """[L, ...] layer-stacked pytree → [S, L//S, ...] stage-stacked."""
    def reshape(x):
        L = x.shape[0]
        assert L % num_stages == 0, (
            f"layer count {L} not divisible by {num_stages} stages")
        return x.reshape((num_stages, L // num_stages) + x.shape[1:])
    return jax.tree_util.tree_map(reshape, params)


def unstack_stage_params(params):
    """[S, L//S, ...] → [L, ...]."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), params)


def _tick_to_micro_batch(t, stage_id, num_stages):
    """Closed form of TrainSchedule._step_to_micro_batch (see module doc).

    Works elementwise on traced values (stage_id is `lax.axis_index`).
    Returns (micro_batch_id, is_forward); the id is unclipped — callers
    mask with 0 <= id < M.
    """
    is_fwd = (t % 2) == (stage_id % 2)
    m = jnp.where(is_fwd,
                  t // 2 - stage_id // 2,
                  t // 2 - num_stages + 1 + stage_id // 2)
    return m, is_fwd


def num_pipe_buffers(num_stages, micro_batches):
    """Rotating stage-input slots needed by the 1F1B interleave: stage s
    sees fwd(m) at tick 2m+s and bwd(m) at 2m+2S-1-s, so at most S - s
    inputs are live at once (reference schedule.py:243-247)."""
    return max(2, min(num_stages + 1, micro_batches))


def _pvary(x):
    """Mark a replicated value as pipe-varying so it can seed scan carries
    that collectives/conditionals make device-varying. Nothing
    differentiates through these programs (the custom VJP is the backward),
    so the cast has no transpose cost."""
    return mesh_lib.pvary(x, (mesh_lib.PIPE_AXIS,))


def _psum_pipe(x):
    """psum over 'pipe'; upcast on CPU where XLA's AllReducePromotion pass
    crashes on bf16 all-reduce emitted from manual regions."""
    if is_tpu_backend():
        return jax.lax.psum(x, mesh_lib.PIPE_AXIS)
    return jax.lax.psum(x.astype(jnp.float32),
                        mesh_lib.PIPE_AXIS).astype(x.dtype)


def _make_forward_program(stage_fn, M, S, interleave, fwd_perm, shard,
                          param_specs):
    """Forward fill/drain tick program, shared by the training pipeline
    (as the custom-vjp primal) and `pipeline_infer` (as the executed
    InferenceSchedule): stage i computes micro m at tick t = m + i over
    M + S - 1 ticks — exactly InferenceSchedule's step→µbatch mapping
    (runtime/pipe/schedule.py:138, `micro_batch_id = step_id - stage_id`);
    the rotating activation hop (ppermute) is its 2-slot buffer."""
    @functools.partial(shard, in_specs=(param_specs, P()), out_specs=P())
    def _forward_program(sp, mb):
        local = jax.tree_util.tree_map(lambda p: p[0], sp)
        idx = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        zero_mb = jnp.zeros_like(mb[0])

        def tick(carry, t):
            recv_act, out_buf = carry
            m = t - idx                      # fill/drain: stage i runs m = t - i
            valid = (m >= 0) & (m < M)
            x = jnp.where(idx == 0, mb[jnp.clip(t, 0, M - 1)], recv_act)
            if interleave:
                # skip garbage fill/drain ticks (collective-free body)
                y = jax.lax.cond(valid, lambda xx: stage_fn(local, xx),
                                 lambda xx: jnp.zeros_like(xx), x)
            else:
                # uniform: every device runs the body every tick so any
                # auto-axis collectives inside stay aligned
                y = stage_fn(local, x)
            is_out = valid & (idx == S - 1)
            slot = jnp.clip(m, 0, M - 1)
            out_buf = jnp.where(
                is_out,
                jax.lax.dynamic_update_index_in_dim(out_buf, y, slot, 0),
                out_buf)
            recv_act = jax.lax.ppermute(y, mesh_lib.PIPE_AXIS, fwd_perm)
            return (recv_act, out_buf), None

        out_buf0 = _pvary(jnp.zeros_like(mb))
        (_, out_buf), _ = jax.lax.scan(
            tick, (_pvary(zero_mb), out_buf0), jnp.arange(M + S - 1))
        # broadcast the last stage's results to every stage so downstream
        # (loss) code is stage-agnostic
        return _psum_pipe(jnp.where(idx == S - 1, out_buf,
                                    jnp.zeros_like(out_buf)))
    return _forward_program


def _nonpipe_axes_in_param_specs(stage_params):
    """Mesh axes other than 'pipe' that appear in the stage params'
    shardings. A param sharded over a live data/model axis forces GSPMD to
    insert a collective (all-gather / reduce-scatter) inside the stage
    body, which is exactly the thing the interleaved schedule cannot
    tolerate.

    Inspects concrete-array `.sharding` (eager callers) and falls back to
    `.aval.sharding` (explicit-sharding tracers). Under plain jit in Auto
    mode tracers expose neither — that path is covered by the jaxpr scan
    in `_collective_axes_in_body` for explicit collectives; GSPMD-inserted
    ones are undetectable at trace time (documented limitation)."""
    axes = set()
    for leaf in jax.tree_util.tree_leaves(stage_params):
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is None:
            spec = getattr(
                getattr(getattr(leaf, "aval", None), "sharding", None),
                "spec", None)
        if spec is None:
            continue
        for entry in spec:
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            axes.update(n for n in names if n != mesh_lib.PIPE_AXIS)
    return axes


def _axis_names_in_jaxpr(jaxpr, found):
    """Collect mesh-axis names referenced by collective-style primitives
    (psum/ppermute/all_gather/... carry them in 'axes'/'axis_name' params),
    recursing into sub-jaxprs (scan/cond/closed_call/shard_map bodies)."""
    for eqn in jaxpr.eqns:
        for key in ("axes", "axis_name"):
            v = eqn.params.get(key)
            if isinstance(v, str):
                found.add(v)
            elif isinstance(v, (tuple, list, frozenset, set)):
                found.update(n for n in v if isinstance(n, str))
        for v in eqn.params.values():
            sub = getattr(v, "jaxpr", v)
            if hasattr(sub, "eqns"):
                _axis_names_in_jaxpr(sub, found)
            elif isinstance(v, (tuple, list)):
                for w in v:
                    subw = getattr(w, "jaxpr", w)
                    if hasattr(subw, "eqns"):
                        _axis_names_in_jaxpr(subw, found)


def _collective_axes_in_body(stage_fn, stage_params, microbatches, live):
    """Best-effort trace of the stage body looking for explicit collectives
    over live non-pipe mesh axes (ring attention's ppermute over 'seq', a
    hand-written psum over 'model', ...). Works on tracers too — the trace
    is abstract. Returns the offending axis names (empty = no proof).

    A trace failure that names a live axis (unbound axis name) is itself
    proof the body references that axis."""
    try:
        local_abs = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype),
            stage_params)
        x_abs = jax.ShapeDtypeStruct(microbatches.shape[1:],
                                     microbatches.dtype)
        jaxpr = jax.make_jaxpr(stage_fn)(local_abs, x_abs)
        found = set()
        _axis_names_in_jaxpr(jaxpr.jaxpr, found)
        return found & live
    except Exception as e:
        # JAX reports a collective over a mesh axis traced outside its
        # binding as "unbound axis name: <axis>" — that exact failure IS
        # the proof. Any other trace failure proves nothing; stay silent
        # (the real error will resurface when the actual program traces).
        msg = str(e)
        if "unbound axis name" in msg:
            return {a for a in live if a in msg}
        return set()


def _pipeline_prologue(stage_params, microbatches, mesh, interleave,
                       stage_fn=None):
    """Shared setup for the training and inference executors: resolves the
    interleave mode (hard error on the forced-interleave + live-ZeRO/TP-spec
    hazard, warning for the maybe-collective-free case), permutations, param
    specs and the pipe-only shard_map.
    Returns None when S == 1 (callers fall back to a sequential map)."""
    S = mesh.shape[mesh_lib.PIPE_AXIS]
    if S == 1:
        return None
    others = 1
    for name, size in mesh.shape.items():
        if name != mesh_lib.PIPE_AXIS:
            others *= size
    if interleave is None:
        interleave = others == 1
    elif interleave and others > 1:
        # forced interleave on a mesh with live data/model/seq axes: any
        # GSPMD collective inside the stage body lands in diverging
        # lax.cond branches and the devices DEADLOCK (see module doc).
        # When the stage params carry ZeRO/TP specs over those axes the
        # collective is GUARANTEED (GSPMD must gather the shards to apply
        # the layer), so refuse to build a program that cannot run.
        # Otherwise (replicated params, batch-sharded elementwise body may
        # be collective-free) keep the warning.
        live = {k: v for k, v in mesh.shape.items()
                if k != mesh_lib.PIPE_AXIS and v > 1}
        spec_axes = _nonpipe_axes_in_param_specs(stage_params) & live.keys()
        if not spec_axes and stage_fn is not None:
            spec_axes = _collective_axes_in_body(
                stage_fn, stage_params, microbatches, live.keys())
        if spec_axes:
            raise ValueError(
                f"pipeline interleave=True is impossible on this mesh: the "
                f"stage params/body use live non-pipe axes "
                f"{sorted(spec_axes)} (mesh {live}), so collectives land "
                f"inside the interleaved schedule's diverging lax.cond "
                f"branches and the devices deadlock. Use interleave=False "
                f"(the uniform schedule composes with ZeRO/TP/SP) or drop "
                f"the ZeRO/TP specs from the stage params.")
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            "pipeline interleave=True forced on a mesh with non-pipe axes "
            "%s: the stage body must be collective-free or the program "
            "deadlocks; the uniform schedule composes safely", live)

    M = microbatches.shape[0]
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    param_specs = jax.tree_util.tree_map(
        lambda x: P(mesh_lib.PIPE_AXIS, *([None] * (x.ndim - 1))),
        stage_params)
    shard = functools.partial(
        mesh_lib.shard_map, mesh=mesh,
        axis_names=frozenset({mesh_lib.PIPE_AXIS}))
    return S, M, interleave, fwd_perm, param_specs, shard


def pipeline_infer(stage_fn, stage_params, microbatches, mesh,
                   interleave=None):
    """Execute the InferenceSchedule: forward-only pipelining of M
    microbatches through S stages (the role of the reference's
    _exec_schedule interpreting InferenceSchedule,
    pipe/engine.py:1209 + schedule.py:129). No backward program is built
    and nothing differentiates through this — use for eval/serving.

    Same contract as pipeline_1f1b's forward: returns the last stage's
    outputs [M, ...], replicated over 'pipe'.
    """
    setup = _pipeline_prologue(stage_params, microbatches, mesh, interleave,
                               stage_fn=stage_fn)
    if setup is None:
        squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return jax.lax.map(lambda x: stage_fn(squeezed, x), microbatches)
    S, M, interleave, fwd_perm, param_specs, shard = setup
    program = _make_forward_program(stage_fn, M, S, interleave, fwd_perm,
                                    shard, param_specs)
    return program(stage_params, microbatches)


def pipeline_1f1b(stage_fn, stage_params, microbatches, mesh,
                  interleave=None):
    """Run M microbatches through S = mesh.shape['pipe'] stages; returns the
    last stage's outputs [M, ...] (replicated over 'pipe').

    stage_fn(stage_local_params, x) -> y with y.shape == x.shape.
    stage_params: pytree, every leaf with leading stage dim S.
    microbatches: [M, mb, ...] activations entering stage 0.
    interleave: True → reference 1F1B interleaved ticks (stage body must be
      collective-free, see module doc); False → uniform ticks (composes
      with ZeRO/TP/SP); None → auto (interleave iff 'pipe' is the only
      non-trivial mesh axis).

    Differentiable: gradients flow to both stage_params and microbatches
    through the hand-written backward program.

    Only the 'pipe' axis is shard_mapped — data/seq/model stay in GSPMD
    auto mode, so ZeRO/TP/SP shardings compose untouched.
    """
    setup = _pipeline_prologue(stage_params, microbatches, mesh, interleave,
                               stage_fn=stage_fn)
    if setup is None:
        squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return jax.lax.map(lambda x: stage_fn(squeezed, x), microbatches)
    S, M, interleave, fwd_perm, param_specs, shard = setup
    NB = num_pipe_buffers(S, M) if interleave else M
    bwd_perm = [((i + 1) % S, i) for i in range(S)]

    def local_params(params_sharded):
        # [1, ...] per-device leaf -> drop the stage dim
        return jax.tree_util.tree_map(lambda p: p[0], params_sharded)

    # ---- forward: GPipe fill/drain, nothing saved ------------------------
    _forward_program = _make_forward_program(stage_fn, M, S, interleave,
                                             fwd_perm, shard, param_specs)

    # ---- backward: even/odd 1F1B replay (interleaved) --------------------
    dparam_specs = param_specs

    @functools.partial(shard, in_specs=(param_specs, P(), P()),
                       out_specs=(dparam_specs, P()))
    def _backward_interleaved(sp, mb, douts):
        local = local_params(sp)
        idx = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        zero_mb = jnp.zeros_like(mb[0])

        def tick(carry, t):
            recv_act, recv_grad, act_buf, dparams, dmb = carry
            m, is_fwd = _tick_to_micro_batch(t, idx, S)
            valid = (m >= 0) & (m < M)
            mc = jnp.clip(m, 0, M - 1)
            slot = mc % NB

            def do_fwd(c):
                _, _, act_buf, dparams, dmb = c
                x = jnp.where(idx == 0, mb[mc], recv_act)
                act_buf = jax.lax.dynamic_update_index_in_dim(
                    act_buf, x, slot, 0)
                y = stage_fn(local, x)
                return act_buf, dparams, dmb, y, jnp.zeros_like(x)

            def do_bwd(c):
                _, _, act_buf, dparams, dmb = c
                x = jax.lax.dynamic_index_in_dim(act_buf, slot, 0,
                                                 keepdims=False)
                g = jnp.where(idx == S - 1, douts[mc], recv_grad)
                _, vjp_fn = jax.vjp(stage_fn, local, x)
                dp, dx = vjp_fn(g)
                dparams = jax.tree_util.tree_map(jnp.add, dparams, dp)
                dmb_upd = jax.lax.dynamic_update_index_in_dim(dmb, dx, mc, 0)
                dmb = jnp.where(idx == 0, dmb_upd, dmb)
                return act_buf, dparams, dmb, jnp.zeros_like(x), dx

            def noop(c):
                _, _, act_buf, dparams, dmb = c
                z = _pvary(jnp.zeros_like(zero_mb))
                return act_buf, dparams, dmb, z, z

            act_buf, dparams, dmb, send_act, send_grad = jax.lax.cond(
                valid & is_fwd, do_fwd,
                lambda c: jax.lax.cond(valid, do_bwd, noop, c), carry)
            recv_act = jax.lax.ppermute(send_act, mesh_lib.PIPE_AXIS,
                                        fwd_perm)
            recv_grad = jax.lax.ppermute(send_grad, mesh_lib.PIPE_AXIS,
                                         bwd_perm)
            return (recv_act, recv_grad, act_buf, dparams, dmb), None

        carry0 = (
            _pvary(zero_mb),                            # recv_act
            _pvary(zero_mb),                            # recv_grad
            _pvary(jnp.zeros((NB,) + mb.shape[1:], mb.dtype)),  # act_buf
            jax.tree_util.tree_map(jnp.zeros_like, local),
            _pvary(jnp.zeros_like(mb)),                 # dmb
        )
        (_, _, _, dparams, dmb), _ = jax.lax.scan(
            tick, carry0, jnp.arange(2 * (M + S - 1)))
        # dmb lives on stage 0 only; replicate. dparams are per-stage and
        # re-stack over the pipe axis via the out_spec.
        dmb = _psum_pipe(dmb)
        dparams = jax.tree_util.tree_map(lambda g: g[None], dparams)
        return dparams, dmb

    # ---- backward: uniform ticks (composes with ZeRO/TP/SP) --------------

    @functools.partial(shard, in_specs=(param_specs, P(), P()),
                       out_specs=(dparam_specs, P()))
    def _backward_uniform(sp, mb, douts):
        local = local_params(sp)
        idx = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        zero_mb = jnp.zeros_like(mb[0])

        def fwd_tick(carry, t):
            recv_act, act_buf = carry
            m = t - idx
            valid = (m >= 0) & (m < M)
            mc = jnp.clip(m, 0, M - 1)
            x = jnp.where(idx == 0, mb[jnp.clip(t, 0, M - 1)], recv_act)
            act_buf = jnp.where(
                valid,
                jax.lax.dynamic_update_index_in_dim(act_buf, x, mc, 0),
                act_buf)
            y = stage_fn(local, x)
            recv_act = jax.lax.ppermute(y, mesh_lib.PIPE_AXIS, fwd_perm)
            return (recv_act, act_buf), None

        (_, act_buf), _ = jax.lax.scan(
            fwd_tick,
            (_pvary(zero_mb),
             _pvary(jnp.zeros((M,) + mb.shape[1:], mb.dtype))),
            jnp.arange(M + S - 1))

        def bwd_tick(carry, u):
            recv_grad, dparams, dmb = carry
            # reverse drain: stage i does bwd of m = u - (S - 1 - i)
            m = u - (S - 1 - idx)
            valid = (m >= 0) & (m < M)
            mc = jnp.clip(m, 0, M - 1)
            x = jax.lax.dynamic_index_in_dim(act_buf, mc, 0, keepdims=False)
            g = jnp.where(idx == S - 1, douts[mc], recv_grad)
            g = jnp.where(valid, g, jnp.zeros_like(g))
            _, vjp_fn = jax.vjp(stage_fn, local, x)
            dp, dx = vjp_fn(g)
            # garbage ticks ran the vjp (to keep collectives aligned) but
            # must contribute exactly zero; the zeroed cotangent makes dp/dx
            # zero by linearity ONLY if the stale buffer input produced
            # finite intermediates (0×Inf = NaN), so mask explicitly
            dp = jax.tree_util.tree_map(
                lambda a: jnp.where(valid, a, jnp.zeros_like(a)), dp)
            dx = jnp.where(valid, dx, jnp.zeros_like(dx))
            dparams = jax.tree_util.tree_map(jnp.add, dparams, dp)
            dmb_upd = jax.lax.dynamic_update_index_in_dim(dmb, dx, mc, 0)
            dmb = jnp.where((idx == 0) & valid, dmb_upd, dmb)
            recv_grad = jax.lax.ppermute(dx, mesh_lib.PIPE_AXIS, bwd_perm)
            return (recv_grad, dparams, dmb), None

        carry0 = (
            _pvary(zero_mb),
            jax.tree_util.tree_map(jnp.zeros_like, local),
            _pvary(jnp.zeros_like(mb)),
        )
        (_, dparams, dmb), _ = jax.lax.scan(
            bwd_tick, carry0, jnp.arange(M + S - 1))
        dmb = _psum_pipe(dmb)
        dparams = jax.tree_util.tree_map(lambda g: g[None], dparams)
        return dparams, dmb

    _backward_program = _backward_interleaved if interleave \
        else _backward_uniform

    @jax.custom_vjp
    def run(sp, mb):
        return _forward_program(sp, mb)

    def run_fwd(sp, mb):
        return _forward_program(sp, mb), (sp, mb)

    def run_bwd(res, douts):
        sp, mb = res
        return _backward_program(sp, mb, douts)

    run.defvjp(run_fwd, run_bwd)
    return run(stage_params, microbatches)
