"""Ulysses-style sequence parallelism — all-to-all head scatter.

The second of the two modern long-context strategies this rebuild provides
(with ring attention, parallel/ring_attention.py) as the upgrade of the
reference's single-device sparse-attention story (SURVEY §5.7). The design
is DeepSpeed-Ulysses (arXiv:2309.14509): activations arrive sequence-
sharded [B, S/n, H, D]; an all_to_all over the `seq` axis re-shards them to
head-sharded [B, S, H/n, D]; each device runs EXACT full-sequence attention
over its head subset (flash kernel); a reverse all_to_all restores sequence
sharding. Communication is O(B·S·E/n) per direction — constant in n vs
ring's n-step pipeline — and rides ICI.

Requires n_head % axis_size == 0. Works under autodiff (all_to_all
transposes to the reverse all_to_all).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib


def _a2a(x, axis_name, scatter_dim, gather_dim):
    """all_to_all wrapper on a local block: scatter `scatter_dim` over the
    axis, gather `gather_dim` from it."""
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_dim,
                              concat_axis=gather_dim, tiled=True)


def ulysses_attention(q, k, v, mesh, causal=False, scale=None,
                      axis: str = mesh_lib.SEQ_AXIS):
    """[B, H, S, D] attention with S sharded over ``axis`` (Ulysses).

    Inputs may be replicated or seq-sharded; GSPMD reshards to the
    in_specs. Output shards like q ([B, H, S, D] with S over ``axis``).
    """
    n = mesh.shape.get(axis, 1)
    B, H, S, D = q.shape
    scale = float(scale) if scale is not None \
        else 1.0 / float(np.sqrt(D))  # sync-ok: python scalar at trace time
    if n == 1:
        from deepspeed_tpu.ops.attention import dot_product_attention
        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    assert H % n == 0, f"n_head {H} not divisible by seq axis {n}"
    assert S % n == 0, f"seq len {S} not divisible by seq axis {n}"
    # pass batch/head tiles through manually when live (see ring_attention);
    # the head axis additionally needs H/tp to stay divisible by n for the
    # in-body head-scatter all_to_all
    tp_axis = mesh_lib.passthrough_axis(mesh, mesh_lib.MODEL_AXIS, H)
    if tp_axis is not None and (H // mesh.shape[tp_axis]) % n != 0:
        tp_axis = None
    spec = P(mesh_lib.passthrough_axis(mesh, mesh_lib.DATA_AXIS, B),
             tp_axis, axis, None)

    @functools.partial(
        mesh_lib.shard_map, mesh=mesh, axis_names=frozenset({axis}),
        in_specs=(spec, spec, spec), out_specs=spec)
    def run(ql, kl, vl):
        # local blocks [B, H, S/n, D] → head-sharded full-seq
        # [B, H/n, S, D]: scatter heads (dim 1), gather sequence (dim 2)
        qh = _a2a(ql, axis, 1, 2)
        kh = _a2a(kl, axis, 1, 2)
        vh = _a2a(vl, axis, 1, 2)
        from deepspeed_tpu.ops.attention import dot_product_attention
        oh = dot_product_attention(qh, kh, vh, causal=causal, scale=scale)
        # back: scatter sequence (dim 2), gather heads (dim 1)
        return _a2a(oh, axis, 2, 1)

    return run(q, k, v)
