"""Ring attention — sequence/context parallelism over ICI.

The reference's long-context story is single-device block-sparse attention
(SURVEY §5.7); ring attention is the modern distributed upgrade this rebuild
provides as a first-class axis: the sequence dim is sharded over the 'seq'
mesh axis, K/V blocks rotate around the ring with `ppermute` while each
device accumulates online-softmax partial results for its local Q block —
exact attention over the full sequence with O(S/n) memory per device and
compute/communication overlap on ICI (Liu et al. 2023, Ring Attention).

Numerics: accumulators (o, m, l) in fp32; K/V travel in their compute dtype.
Works under autodiff (ppermute transposes to the reverse rotation).
"""

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import mesh as mesh_lib

NEG_INF = -1e30


def ring_attention(q, k, v, mesh, causal=False, scale=None,
                   axis: str = mesh_lib.SEQ_AXIS):
    """[B, H, S, D] attention with S sharded over ``axis``.

    Accepts fully-replicated or seq-sharded inputs (GSPMD reshards to the
    in_specs); returns output sharded the same way as q.
    """
    n = mesh.shape.get(axis, 1)
    B, H, S, D = q.shape
    scale = float(scale) if scale is not None \
        else 1.0 / float(np.sqrt(D))  # sync-ok: python scalar at trace time
    if n == 1:
        from deepspeed_tpu.ops.attention import dot_product_attention
        return dot_product_attention(q, k, v, causal=causal, scale=scale)

    assert S % n == 0, f"seq len {S} not divisible by seq axis {n}"
    chunk = S // n
    perm = [(j, (j + 1) % n) for j in range(n)]
    # name the batch/head mesh axes too (when live and divisible): the body
    # is fully batch/head-parallel, and under the full-manual shard_map
    # lowering (mesh_lib.shard_map on jax 0.4.x) an unnamed-but-sharded
    # axis would otherwise replicate q/k/v at entry — an involuntary
    # full-remat on dp x sp x tp meshes
    b_ax = mesh_lib.passthrough_axis(mesh, mesh_lib.DATA_AXIS, B)
    h_ax = mesh_lib.passthrough_axis(mesh, mesh_lib.MODEL_AXIS, H)
    spec = P(b_ax, h_ax, axis, None)
    # per-device block sizes for the scan carries
    Bl = B // (mesh.shape[b_ax] if b_ax else 1)
    Hl = H // (mesh.shape[h_ax] if h_ax else 1)

    @functools.partial(
        mesh_lib.shard_map, mesh=mesh, axis_names=frozenset({axis}),
        in_specs=(spec, spec, spec), out_specs=spec)
    def run(ql, kl, vl):
        idx = jax.lax.axis_index(axis)
        qf = ql.astype(jnp.float32)

        q_pos = idx * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (chunk, chunk), 0)

        def step(carry, s):
            o, m, l, kc, vc = carry
            src = (idx - s) % n  # which global chunk kc/vc currently is
            sc = jnp.einsum("bhqd,bhkd->bhqk", qf,
                            kc.astype(jnp.float32)) * scale
            if causal:
                k_pos = src * chunk + jax.lax.broadcasted_iota(
                    jnp.int32, (chunk, chunk), 1)
                sc = jnp.where((q_pos >= k_pos)[None, None], sc, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(sc - m_new[..., None])
            l_new = l * alpha + jnp.sum(p, axis=-1)
            o_new = o * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
            # rotate K/V one hop around the ring
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (o_new, m_new, l_new, kc, vc), None

        zeros_f32 = functools.partial(jnp.zeros, dtype=jnp.float32)
        var = lambda x: mesh_lib.pvary(x, (axis,))  # noqa: E731
        o0 = var(zeros_f32((Bl, Hl, chunk, D)))
        m0 = var(jnp.full((Bl, Hl, chunk), NEG_INF, jnp.float32))
        l0 = var(zeros_f32((Bl, Hl, chunk)))
        (o, m, l, _, _), _ = jax.lax.scan(
            step, (o0, m0, l0, kl, vl), jnp.arange(n))
        l_safe = jnp.maximum(l, 1e-30)
        return (o / l_safe[..., None]).astype(ql.dtype)

    return run(q, k, v)
