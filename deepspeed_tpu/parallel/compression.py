"""1-bit compressed collectives over ICI — rebuild of the reference's
compressed-communication backends (runtime/comm/nccl.py:47-186 `NcclBackend.
compressed_allreduce`, runtime/comm/mpi.py:14 `MpiBackend`, cupy bit packing
in runtime/compression/cupy.py:10).

The reference's algorithm (error-compensated 1-bit Adam, two-level error
feedback):

  1. worker compensates its buffer with its local worker_error,
     computes one fp32 scale = ||buf|| / sqrt(numel), packs sign bits,
     records the new worker_error = buf - scale*sign(buf);
  2. all_to_all: worker i receives everyone's sign-chunk i (+ allgather of
     the scales), decompresses and averages its chunk — the "server" role
     is sharded round-robin over workers;
  3. the server chunk is itself compensated (server_error), re-compressed
     to sign+scale, and allgathered back to every worker.

TPU-native mapping: the collectives are `jax.lax.all_to_all`/`all_gather`
over a named mesh axis inside `shard_map` (ICI within a slice, DCN across
slices — XLA routes by mesh position); cupy packbits becomes a vectorized
bit-pack to uint8 (×32 payload shrink vs fp32, ×8 vs the sign bytes). The
two error-feedback tensors are *per-device* state: worker_error is
[numel]-shaped on every worker, server_error is [numel/n]-shaped (one chunk
per worker).

Everything here is pure and jit-able; functions taking ``axis_name`` must
run inside `shard_map` (or `pmap`) that binds the axis.
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import mesh as mesh_lib


_BIT_WEIGHTS = 2 ** np.arange(8, dtype=np.uint8)  # LSB-first packing


def pack_signs(x):
    """[N] float → [N/8] uint8 bitmap, bit j of byte i = (x[8i+j] >= 0).
    N must be a multiple of 8."""
    bits = (x >= 0).reshape(-1, 8).astype(jnp.uint8)
    return (bits * jnp.asarray(_BIT_WEIGHTS)).sum(axis=1).astype(jnp.uint8)


def unpack_signs(packed, dtype=jnp.float32):
    """[M] uint8 bitmap → [8M] ±1 values of `dtype`."""
    bits = jnp.bitwise_and(
        packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :], 1)
    return (bits.astype(dtype) * 2.0 - 1.0).reshape(-1)


def _scale_of(x):
    # reference scale: ||x||_2 / sqrt(numel)  (nccl.py:66)
    return jnp.linalg.norm(x) / np.sqrt(x.size)


def compressed_allreduce(buf, worker_error, server_error, axis_name):
    """Error-compensated 1-bit mean-allreduce of ``buf`` over ``axis_name``.

    Must run inside shard_map binding ``axis_name``. ``buf`` is the local
    [numel] fp32 buffer (same shape on every device, numel divisible by
    8*axis_size); ``worker_error`` is [numel], ``server_error`` is
    [numel // axis_size], both per-device.

    Returns (result, new_worker_error, new_server_error): ``result`` is the
    approximate mean of ``buf`` over the axis, identical on all devices.
    """
    n = mesh_lib.axis_size(axis_name)
    numel = buf.size
    assert numel % (8 * n) == 0, (
        f"1-bit buffer numel {numel} must divide by 8*axis={8 * n}")
    chunk = numel // n

    # -- worker side: compensate, compress ------------------------------
    compensated = buf + worker_error
    worker_scale = _scale_of(compensated)
    new_worker_error = compensated - worker_scale * jnp.sign(compensated)
    packed = pack_signs(compensated)                       # [numel/8] u8

    # -- exchange: chunk i of every worker → worker i -------------------
    # [n, chunk/8] rows; row i goes to worker i, rows arrive stacked by
    # source worker
    packed = packed.reshape(n, chunk // 8)
    recv = jax.lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(worker_scale, axis_name)   # [n]

    # -- server side: decompress+average my chunk, re-compress ----------
    signs = unpack_signs(recv.reshape(-1)).reshape(n, chunk)
    avg = (signs * scales[:, None]).mean(axis=0)           # [chunk]
    server_comp = avg + server_error
    server_scale = _scale_of(server_comp)
    new_server_error = server_comp - server_scale * jnp.sign(server_comp)
    server_packed = pack_signs(server_comp)                # [chunk/8]

    # -- gather the servers' results back to everyone -------------------
    all_packed = jax.lax.all_gather(server_packed, axis_name)  # [n, chunk/8]
    all_scales = jax.lax.all_gather(server_scale, axis_name)   # [n]
    out = unpack_signs(all_packed.reshape(-1)).reshape(n, chunk) \
        * all_scales[:, None]
    return out.reshape(buf.shape), new_worker_error, new_server_error


def hierarchical_allreduce(buf, inter_axis, intra_axis):
    """Exact two-level mean-allreduce of ``buf`` (the uncompressed leg of
    the link-aware exchange, ISSUE 10): ring reduce-scatter over the fast
    ``intra_axis`` (each device ends with one chunk of the intra-group
    sum), one mean over the slow ``inter_axis`` of just that chunk (XLA
    picks the algorithm for the DCN-class hop), ring all-gather back over
    the fast axis. Must run inside shard_map binding both axes;
    ``buf.size`` must divide by the intra axis size. Matches a flat pmean
    over both axes to fp32 ring-order rounding."""
    from deepspeed_tpu.parallel import overlap
    k = mesh_lib.axis_size(intra_axis)
    shard = overlap.ring_reduce_scatter(buf, intra_axis, k)
    shard = jax.lax.pmean(shard, inter_axis) * np.float32(1.0 / k)
    return overlap.ring_all_gather(shard, intra_axis, k).reshape(buf.shape)


def hierarchical_compressed_allreduce(buf, worker_error, server_error,
                                      inter_axis, intra_axis):
    """Link-aware 1-bit mean-allreduce (ISSUE 10): only the slow
    inter-host hop is compressed.

      1. ring reduce-scatter over the fast ``intra_axis`` (uncompressed —
         ICI-class links, compression would cost more than it saves) and
         fold in the intra mean: each device holds chunk ``intra_index``
         of its group's mean;
      2. the error-compensated 1-bit exchange (`compressed_allreduce`) of
         that chunk over the slow ``inter_axis`` — sign bits + one scale
         on the DCN-class wire, ~32x fewer payload bytes than fp32;
      3. ring all-gather over the fast axis to rebuild the full buffer.

    Per-device error state is chunk-shaped: ``worker_error``
    [numel/intra], ``server_error`` [numel/(intra*inter)]; ``buf.size``
    must divide by 8*inter*intra (pad via `padded_numel(numel,
    inter*intra)`). Returns (approx_mean, new_worker_error,
    new_server_error) — the result is identical on every device."""
    from deepspeed_tpu.parallel import overlap
    k = mesh_lib.axis_size(intra_axis)
    shard = overlap.ring_reduce_scatter(buf, intra_axis, k) \
        * np.float32(1.0 / k)
    red, we2, se2 = compressed_allreduce(shard, worker_error, server_error,
                                         inter_axis)
    return (overlap.ring_all_gather(red, intra_axis, k).reshape(buf.shape),
            we2, se2)


def compressed_reduce_scatter_sum(buf, worker_error, axis_name):
    """Error-compensated 1-bit reduce-scatter-SUM of ``buf`` over
    ``axis_name`` (ISSUE 16): the worker half of `compressed_allreduce`
    with no server leg — the output stays scattered, so there is nothing
    to re-compress and gather back.

    ``buf`` is the local [numel] fp32 buffer laid out piece-major: chunk
    ``j`` (of ``numel // axis_size`` elements) is destined for axis peer
    ``j``. Each worker compensates with its persistent ``worker_error``
    ([numel], per-device), compresses to sign bits + one fp32 scale,
    all-to-alls the sign chunks, and returns the weighted SUM (not mean —
    the ZeRO-3 grad contract hands the caller fp32 sums, the 1/world
    scale is applied downstream) of its own chunk over all peers:

        chunk_sum[j] = sum_i  scale_i * sign(buf_i + err_i)[my chunk]

    Returns (chunk_sum [numel/n], new_worker_error [numel]). ``numel``
    must divide by 8*axis_size (pad via `padded_numel`). Slow-hop wire
    cost per device: (n-1)/n of numel/8 sign bytes + n-1 scale floats —
    vs (n-1)/n * numel * 4 bytes for the exact ring reduce-scatter."""
    n = mesh_lib.axis_size(axis_name)
    numel = buf.size
    assert numel % (8 * n) == 0, (
        f"1-bit RS buffer numel {numel} must divide by 8*axis={8 * n}")
    chunk = numel // n

    compensated = buf + worker_error
    worker_scale = _scale_of(compensated)
    new_worker_error = compensated - worker_scale * jnp.sign(compensated)
    packed = pack_signs(compensated).reshape(n, chunk // 8)
    recv = jax.lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(worker_scale, axis_name)   # [n]
    signs = unpack_signs(recv.reshape(-1)).reshape(n, chunk)
    chunk_sum = (signs * scales[:, None]).sum(axis=0)      # [chunk]
    return chunk_sum, new_worker_error


def padded_numel(numel, axis_size):
    """Smallest buffer size >= numel divisible by 8*axis_size."""
    q = 8 * axis_size
    return ((numel + q - 1) // q) * q


def tree_compressed_allreduce(tree, worker_errors, server_errors, axis_name):
    """Per-leaf compressed allreduce of a pytree (the reference fuses the
    whole momentum into one flat buffer per tensor, onebit/adam.py:191).
    Leaves are padded to the 8*axis_size quantum; error states carry the
    padded length."""
    n = mesh_lib.axis_size(axis_name)

    def one(leaf, we, se):
        flat = leaf.reshape(-1).astype(jnp.float32)
        pn = padded_numel(flat.size, n)
        buf = jnp.zeros((pn,), jnp.float32).at[:flat.size].set(flat)
        out, we2, se2 = compressed_allreduce(buf, we, se, axis_name)
        return out[:flat.size].reshape(leaf.shape), we2, se2

    flat = jax.tree_util.tree_map(one, tree, worker_errors, server_errors)
    pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
        lambda t: t[i], flat, is_leaf=lambda x: isinstance(x, tuple))
    return pick(0), pick(1), pick(2)


def init_error_states(params, axis_size):
    """(worker_errors, server_errors) zero trees for a param tree — worker
    [padded], server [padded/axis]."""
    def we(p):
        return jnp.zeros((padded_numel(p.size, axis_size),), jnp.float32)

    def se(p):
        return jnp.zeros((padded_numel(p.size, axis_size) // axis_size,),
                         jnp.float32)

    return (jax.tree_util.tree_map(we, params),
            jax.tree_util.tree_map(se, params))
