"""Bucketed gradient-sync scheduler — explicit comm/compute overlap.

The fused GSPMD train step expresses the ZeRO grad exchange as one implicit
constraint ("grad reduce-scatter → a sharding constraint", runtime/engine.py
docstring), which leaves XLA free to serialize the WHOLE gradient exchange
after backward. The reference DeepSpeed instead buckets gradients as they
are produced and overlaps each bucket's collective with the remaining
backward compute (`overlap_comm` + the IPG bucket machinery,
stage2.py:614-746). This module is the TPU-native rebuild of that
scheduler:

  * gradients flatten (in tree-leaf order) into fixed-size fp32 buckets
    (config knob ``zero_optimization.reduce_bucket_size``, reference
    constants.py ZERO_REDUCE_BUCKET_SIZE — element count, default 5e8);
  * each bucket's exchange is an EXPLICIT ring program over the data axis
    (`lax.ppermute` hops, like parallel/ring_attention.py): a ring
    reduce-scatter followed by a ring all-gather — an allreduce decomposed
    into 2(n-1) chunk hops whose only data dependency is the bucket's own
    leaves. XLA's latency-hiding scheduler can therefore float bucket k's
    hops over bucket k+1's backward compute and over other buckets' hops,
    where one monolithic post-hoc psum has nothing to overlap with;
  * ``mode="fused"`` keeps the bucket granularity but lets XLA pick the
    collective implementation per bucket (one `lax.psum` each) — the
    fallback when ppermute rings lose to the fused collective on a given
    interconnect (measure; see docs/perf_tuning.md).

Everything here is pure, jit-able, and must run INSIDE `shard_map` binding
the axis (the engine's explicit-comm train path, like parallel/compression).
Numerics: ring summation visits devices in ring order rather than the
reduction tree XLA picks for psum, so results match psum to fp32 rounding
(the numerics test pins this across bucket layouts).

The 1-bit path rides the same bucket stream: `bucketed_compressed_allreduce`
runs parallel/compression.py's error-compensated 1-bit exchange per bucket,
so a bucket is the unit of both overlap and compression.
"""

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# bucket planning (host-side, static)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Bucket:
    """One bucket: a contiguous run of flattened leaves.

    ``leaf_ids`` indexes the flat leaf list; ``sizes`` are the flattened
    element counts; ``padded`` is the bucket's exchange length — total
    elements rounded up to a multiple of the axis size so the ring can
    chunk it evenly (the uneven LAST bucket differs from the rest)."""
    leaf_ids: Tuple[int, ...]
    sizes: Tuple[int, ...]
    padded: int

    @property
    def numel(self):
        return int(sum(self.sizes))


def plan_buckets(shapes: Sequence, bucket_elems: int,
                 axis_size: int) -> List[Bucket]:
    """Greedy whole-leaf packing of ``shapes`` (in order) into buckets of
    ~``bucket_elems`` elements (the reference's IPG bucket close condition,
    stage2.py `elements_in_ipg_bucket + param.numel() > reduce_bucket_size`).
    A leaf larger than the budget gets a bucket of its own; the last bucket
    is whatever is left over (usually uneven)."""
    bucket_elems = max(int(bucket_elems), 1)
    buckets: List[Bucket] = []
    ids: List[int] = []
    sizes: List[int] = []
    acc = 0
    n_leaves = 0
    for i, shape in enumerate(shapes):
        n = int(np.prod(shape or (1,)))
        n_leaves += 1
        if ids and acc + n > bucket_elems:
            buckets.append(_close_bucket(ids, sizes, axis_size))
            ids, sizes, acc = [], [], 0
        ids.append(i)
        sizes.append(n)
        acc += n
    if ids:
        buckets.append(_close_bucket(ids, sizes, axis_size))
    # flight-recorder breadcrumb (trace-time only — planning runs once
    # per compile, never per step): what the bucket stream looked like
    from deepspeed_tpu.telemetry.recorder import default_recorder
    default_recorder().record(
        "overlap_bucket_plan", buckets=len(buckets), leaves=n_leaves,
        elems=sum(b.numel for b in buckets),
        padded_elems=sum(b.padded for b in buckets), axis_size=axis_size,
        bucket_elems=bucket_elems)
    return buckets


def _close_bucket(ids, sizes, axis_size):
    total = int(sum(sizes))
    padded = ((total + axis_size - 1) // axis_size) * axis_size
    return Bucket(tuple(ids), tuple(sizes), padded)


# ---------------------------------------------------------------------------
# ring collectives (per-device local view; inside shard_map)
# ---------------------------------------------------------------------------

def _ring_hops(fn_body, n, unroll_limit=32):
    """n-1 ring hops, unrolled below ``unroll_limit`` so the latency-hiding
    scheduler sees independent ops it can interleave across buckets; a scan
    (sequential while loop) above it to bound HLO size on huge meshes."""
    return n <= unroll_limit


def ring_reduce_scatter(buf, axis_name: str, n: int) -> jax.Array:
    """[n*c] local buffer → [c] shard: this device ends with the sum over
    the axis of chunk ``axis_index``. Standard ring: the partial for chunk k
    is born on device (k+1) mod n and accumulates one local chunk per hop
    until it lands on device k after n-1 hops — c elements on the wire per
    hop per device."""
    assert buf.size % n == 0, (buf.size, n)
    c = buf.size // n
    if n == 1:
        return buf.reshape(c)
    chunks = buf.reshape(n, c)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    carry = jnp.take(chunks, (idx - 1) % n, axis=0, mode="wrap")
    if _ring_hops(None, n):
        for s in range(1, n):
            carry = jax.lax.ppermute(carry, axis_name, perm)
            carry = carry + jnp.take(chunks, (idx - 1 - s) % n, axis=0,
                                     mode="wrap")
    else:
        def hop(carry, s):
            carry = jax.lax.ppermute(carry, axis_name, perm)
            return carry + jnp.take(chunks, (idx - 1 - s) % n, axis=0,
                                    mode="wrap"), None
        carry, _ = jax.lax.scan(hop, carry, jnp.arange(1, n))
    return carry


def ring_all_gather(shard, axis_name: str, n: int) -> jax.Array:
    """[c] shard (this device owns chunk ``axis_index``) → [n*c] full
    buffer, chunks in axis order; the reverse ring of ring_reduce_scatter."""
    if n == 1:
        return shard
    c = shard.size
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    out = jnp.zeros((n, c), shard.dtype)
    out = jax.lax.dynamic_update_index_in_dim(out, shard[None], idx, 0)
    carry = shard
    if _ring_hops(None, n):
        for s in range(1, n):
            carry = jax.lax.ppermute(carry, axis_name, perm)
            out = jax.lax.dynamic_update_index_in_dim(
                out, carry[None], (idx - s) % n, 0)
    else:
        def hop(acc, s):
            out, carry = acc
            carry = jax.lax.ppermute(carry, axis_name, perm)
            out = jax.lax.dynamic_update_index_in_dim(
                out, carry[None], (idx - s) % n, 0)
            return (out, carry), None
        (out, _), _ = jax.lax.scan(hop, (out, carry), jnp.arange(1, n))
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# bucketed tree sync (inside shard_map)
# ---------------------------------------------------------------------------

def _pack_bucket(leaves, bucket: Bucket) -> jax.Array:
    parts = [leaves[i].reshape(-1).astype(jnp.float32)
             for i in bucket.leaf_ids]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if bucket.padded != bucket.numel:
        flat = jnp.zeros((bucket.padded,), jnp.float32).at[:flat.size].set(flat)
    return flat


def _unpack_bucket(flat, leaves, bucket: Bucket, out):
    off = 0
    for i, sz in zip(bucket.leaf_ids, bucket.sizes):
        leaf = leaves[i]
        out[i] = jax.lax.dynamic_slice_in_dim(flat, off, sz, 0) \
            .reshape(leaf.shape).astype(leaf.dtype)
        off += sz


def bucketed_allreduce(tree, axis_name: str, n: int, bucket_elems: int,
                       mode: str = "ring", mean: bool = True):
    """Sum (or mean) a gradient pytree over ``axis_name`` as a stream of
    per-bucket explicit collectives. Must run inside shard_map binding the
    axis with the tree per-device (unreduced local grads).

    mode="ring":  per bucket, ring reduce-scatter + ring all-gather
                  (2(n-1) chunk hops the scheduler can float over compute).
    mode="fused": per bucket, one `lax.psum` (XLA picks the algorithm) —
                  still bucketed, so buckets interleave with backward.
    mode="fused_matmul": the stage-3 tile-granular gather mode (ISSUE
                  8). The replicated-leaf tail this bucket stream
                  carries has no GEMM to fuse into — the weight-grad
                  GEMMs it used to trail behind now reduce-scatter
                  INSIDE the fused matmul+RS kernels
                  (ops/pallas/fused_collective.py), so what is left
                  here exchanges on the plain ppermute ring.
    """
    if mode not in ("ring", "fused", "fused_matmul"):
        raise ValueError(f"mode must be 'ring', 'fused' or "
                         f"'fused_matmul', got {mode!r}")
    if mode == "fused_matmul":
        mode = "ring"
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves or n == 1:
        return tree
    buckets = plan_buckets([l.shape for l in leaves], bucket_elems, n)
    inv = np.float32(1.0 / n)
    out: List[Optional[jax.Array]] = [None] * len(leaves)
    for bucket in buckets:
        flat = _pack_bucket(leaves, bucket)
        if mode == "ring":
            shard = ring_reduce_scatter(flat, axis_name, n)
            flat = ring_all_gather(shard, axis_name, n)
        else:
            flat = jax.lax.psum(flat, axis_name)
        if mean:
            flat = flat * inv
        _unpack_bucket(flat, leaves, bucket, out)
    return jax.tree_util.tree_unflatten(treedef, out)


def bucketed_reduce_scatter(tree, axis_name: str, n: int, bucket_elems: int,
                            mean: bool = True):
    """Ring reduce-scatter only: returns the list of per-bucket [padded/n]
    fp32 shards (this device's chunk of each bucket) plus the bucket plan —
    the ZeRO-2 shape, for callers that update in flat shard space and
    all-gather params instead of grads. The allreduce above is RS∘AG of
    this."""
    leaves, _ = jax.tree_util.tree_flatten(tree)
    buckets = plan_buckets([l.shape for l in leaves], bucket_elems, n)
    shards = []
    inv = np.float32(1.0 / n)
    for bucket in buckets:
        flat = _pack_bucket(leaves, bucket)
        shard = ring_reduce_scatter(flat, axis_name, n)
        shards.append(shard * inv if mean else shard)
    return shards, buckets


def bucketed_compressed_allreduce(tree, worker_errors, server_errors,
                                  axis_name: str, n: int, bucket_elems: int):
    """1-bit error-compensated mean-allreduce riding the bucket stream:
    each bucket is one compression unit (sign-pack → all_to_all → server
    average → all_gather, parallel/compression.py) instead of one unit per
    LEAF (tree_compressed_allreduce) — fewer, larger collectives whose
    exchanges interleave exactly like the ring buckets.

    ``worker_errors``/``server_errors`` are lists aligned with the bucket
    plan of ``tree`` (see `compressed_error_states`). Returns
    (mean_tree, new_worker_errors, new_server_errors)."""
    from deepspeed_tpu.parallel import compression as comp
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    buckets = plan_buckets([l.shape for l in leaves], bucket_elems, n)
    assert len(worker_errors) == len(buckets), \
        (len(worker_errors), len(buckets))
    out: List[Optional[jax.Array]] = [None] * len(leaves)
    new_we, new_se = [], []
    for bucket, we, se in zip(buckets, worker_errors, server_errors):
        flat = _pack_bucket(leaves, bucket)
        pn = comp.padded_numel(bucket.padded, n)
        if pn != flat.size:
            flat = jnp.zeros((pn,), jnp.float32).at[:flat.size].set(flat)
        red, we2, se2 = comp.compressed_allreduce(flat, we, se, axis_name)
        new_we.append(we2)
        new_se.append(se2)
        _unpack_bucket(red[:bucket.padded], leaves, bucket, out)
    return jax.tree_util.tree_unflatten(treedef, out), new_we, new_se


# ---------------------------------------------------------------------------
# hierarchical link-aware exchange (ISSUE 10): per-bucket compression
# policy over a slow/fast split of the data axis
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HierarchyPlan:
    """Static link-aware comm plan for the bucketed exchange: the data
    axis split into ``inter`` slow-link groups (DCN-class,
    ``inter_axis``) of ``intra`` fast-link devices (ICI-class,
    ``intra_axis``), plus the per-bucket compression policy —
    ``"always"``/``"never"``, or ``"auto"``: compress only buckets whose
    fp32 payload clears ``min_bucket_bytes`` (small buckets pay more in
    scale overhead + pack/unpack than the sign bits save)."""
    inter_axis: str
    intra_axis: str
    inter: int
    intra: int
    compression: str = "auto"
    min_bucket_bytes: int = 1 << 16
    bucket_elems: int = int(5e8)

    @property
    def axes(self):
        return (self.inter_axis, self.intra_axis)

    @property
    def world(self):
        return self.inter * self.intra


def plan_bucket_compression(buckets, plan: HierarchyPlan):
    """Per-bucket compress/no-compress decision (host-side, static at
    trace time — the link assignment itself is the plan's axis split).
    Pure: the engine breadcrumbs the plan once per compile
    (`comm_hierarchy_plan`), since this runs from several callers
    (error-state init, the traced exchange, the wire model)."""
    if plan.compression == "always":
        return [True] * len(buckets)
    if plan.compression == "never":
        return [False] * len(buckets)
    return [b.padded * 4 >= plan.min_bucket_bytes for b in buckets]


def bucketed_hierarchical_mean(tree, plan: HierarchyPlan):
    """Exact two-level mean of a gradient pytree riding the bucket
    stream (the warmup-phase exchange of the hierarchical 1-bit path):
    per bucket, ring reduce-scatter over the fast axis → pmean of the
    chunk over the slow axis → ring all-gather. Must run inside
    shard_map binding both plan axes."""
    from deepspeed_tpu.parallel import compression as comp
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    buckets = plan_buckets([l.shape for l in leaves], plan.bucket_elems,
                           plan.world)
    out: List[Optional[jax.Array]] = [None] * len(leaves)
    for bucket in buckets:
        flat = _pack_bucket(leaves, bucket)
        flat = comp.hierarchical_allreduce(flat, plan.inter_axis,
                                           plan.intra_axis)
        _unpack_bucket(flat, leaves, bucket, out)
    return jax.tree_util.tree_unflatten(treedef, out)


def bucketed_hierarchical_compressed_allreduce(tree, worker_errors,
                                               server_errors,
                                               plan: HierarchyPlan):
    """Policy-driven link-aware mean-allreduce of a pytree over the
    bucket stream: buckets the policy compresses run the two-level 1-bit
    exchange (`compression.hierarchical_compressed_allreduce` — slow-axis
    sign bits with error feedback); the rest run the exact two-level
    mean. ``worker_errors``/``server_errors`` are per-bucket lists (None
    entries for uncompressed buckets — see `hierarchical_error_states`).
    Returns (mean_tree, new_worker_errors, new_server_errors)."""
    from deepspeed_tpu.parallel import compression as comp
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    buckets = plan_buckets([l.shape for l in leaves], plan.bucket_elems,
                           plan.world)
    flags = plan_bucket_compression(buckets, plan)
    assert len(worker_errors) == len(buckets), \
        (len(worker_errors), len(buckets))
    out: List[Optional[jax.Array]] = [None] * len(leaves)
    new_we, new_se = [], []
    for bucket, flag, we, se in zip(buckets, flags, worker_errors,
                                    server_errors):
        flat = _pack_bucket(leaves, bucket)
        if flag:
            pn = comp.padded_numel(bucket.padded, plan.world)
            if pn != flat.size:
                flat = jnp.zeros((pn,), jnp.float32) \
                    .at[:flat.size].set(flat)
            red, we2, se2 = comp.hierarchical_compressed_allreduce(
                flat, we, se, plan.inter_axis, plan.intra_axis)
            red = red[:bucket.padded]
        else:
            red = comp.hierarchical_allreduce(flat, plan.inter_axis,
                                              plan.intra_axis)
            we2, se2 = we, se
        new_we.append(we2)
        new_se.append(se2)
        _unpack_bucket(red, leaves, bucket, out)
    return jax.tree_util.tree_unflatten(treedef, out), new_we, new_se


def hierarchical_error_states(params, plan: HierarchyPlan):
    """Zero error-feedback state aligned with the bucket plan AND the
    compression policy of ``params``: compressed buckets carry
    chunk-shaped worker [pn/intra] and server [pn/(intra*inter)] errors;
    uncompressed buckets carry None (nothing to compensate — the None
    rides the pytree as empty structure through the phase cond)."""
    from deepspeed_tpu.parallel import compression as comp
    leaves = jax.tree_util.tree_leaves(params)
    buckets = plan_buckets([l.shape for l in leaves], plan.bucket_elems,
                           plan.world)
    flags = plan_bucket_compression(buckets, plan)
    wes, ses = [], []
    for bucket, flag in zip(buckets, flags):
        if not flag:
            wes.append(None)
            ses.append(None)
            continue
        pn = comp.padded_numel(bucket.padded, plan.world)
        wes.append(jnp.zeros((pn // plan.intra,), jnp.float32))
        ses.append(jnp.zeros((pn // plan.world,), jnp.float32))
    return wes, ses


def hierarchy_wire_bytes(buckets, flags, plan: HierarchyPlan):
    """Trace-time bytes-on-wire cost model (per device, per step) for
    the hierarchical exchange — what the telemetry counters
    ``comm/bytes_on_wire/{intra,inter}`` advance by each step.

    Ring formulas: the fast-axis reduce-scatter + all-gather move
    2(k-1) fp32 chunks of pn/k elements per device; the slow-axis hop
    moves, uncompressed, a ring allreduce of the pn/k chunk
    (2·(ni-1)/ni·4 bytes/elem), or compressed, the packed sign bitmaps
    both ways (all_to_all + server all-gather, (ni-1)/ni·pn/(8k) bytes
    each) plus 2(ni-1) fp32 scales. ``inter_uncompressed`` is the
    would-have-been fp32 cost of the same slow hop — the compression
    denominator the bench's bytes_reduction headline divides by."""
    from deepspeed_tpu.parallel import compression as comp
    k, ni = plan.intra, plan.inter
    intra = inter = inter_unc = 0
    for bucket, flag in zip(buckets, flags):
        pn = comp.padded_numel(bucket.padded, plan.world) if flag \
            else bucket.padded
        c = pn // k
        intra += 2 * (k - 1) * c * 4
        unc = 2 * c * 4 * (ni - 1) // ni
        if flag:
            inter += 2 * (c // 8) * (ni - 1) // ni + 2 * (ni - 1) * 4
        else:
            inter += unc
        inter_unc += unc
    return {"intra": int(intra), "inter": int(inter),
            "inter_uncompressed": int(inter_unc)}


# ---------------------------------------------------------------------------
# two-level piece-ordered collectives for the ZeRO-3 prefetch stream
# (ISSUE 16): the stage-3 gathers/scatters move data in NATURAL data-axis
# order (row i of a [n, c] stack belongs to data index i), and the split
# mesh is row-major (data index = inter_index * intra + intra_index), so
# the two-level schedule is: ONE slow-hop collective of the local shard
# over ``inter_axis``, a fast ring over ``intra_axis`` for the rest, and
# a transpose to restore natural order. Must run inside shard_map
# binding both plan axes.
# ---------------------------------------------------------------------------

def two_level_all_gather(shard, plan: HierarchyPlan):
    """[c] local shard → [n, c] full stack in natural data order.

    Inter hop FIRST (one ``lax.all_gather`` of just the raw shard —
    (ni-1)·c elements on the slow wire per device), then the intra ring
    carries the [ni, c] stacks around the fast links. Intra-first would
    push k× redundant bytes over the slow hop."""
    ni, k = plan.inter, plan.intra
    c = shard.size
    stacked = jax.lax.all_gather(shard.reshape(-1), plan.inter_axis)
    full = ring_all_gather(stacked.reshape(-1), plan.intra_axis, k)
    # rows (t', b') → natural order idx = b'*k + t'
    return full.reshape(k, ni, c).transpose(1, 0, 2).reshape(ni * k, c)


def two_level_reduce_scatter_sum(pieces, plan: HierarchyPlan):
    """[n, c] piece stack (row i destined for data index i) → [c] SUM of
    this device's piece over all n devices. Fast intra ring first (fp32
    partial sums stay on ICI-class links), then ONE exact slow-hop ring
    reduce-scatter of the [ni, c] partials."""
    ni, k = plan.inter, plan.intra
    c = pieces.shape[-1]
    # row t' of the intra ring buffer carries the ni pieces destined for
    # intra position t'
    buf = pieces.reshape(ni, k, c).transpose(1, 0, 2).reshape(-1)
    mine = ring_reduce_scatter(buf, plan.intra_axis, k)   # [ni*c]
    return ring_reduce_scatter(mine, plan.inter_axis, ni)


def two_level_error_numel(c: int, plan: HierarchyPlan) -> int:
    """Persistent worker-error length for a compressed two-level RS of
    [n, c] pieces: the slow-hop buffer is [ni, c8] with each piece padded
    to the sign-pack quantum."""
    return plan.inter * (((int(c) + 7) // 8) * 8)


def two_level_reduce_scatter_compressed(pieces, worker_error,
                                        plan: HierarchyPlan):
    """Like `two_level_reduce_scatter_sum` but the slow hop carries
    error-compensated sign bits (`compression.compressed_reduce_scatter_
    sum`) instead of fp32 — the ZeRO-3 grad legs' compressed inter-host
    hop. ``worker_error`` is the persistent per-device
    [`two_level_error_numel(c, plan)`] residual. Returns
    (piece_sum [c], new_worker_error)."""
    from deepspeed_tpu.parallel import compression as comp
    ni, k = plan.inter, plan.intra
    c = pieces.shape[-1]
    buf = pieces.reshape(ni, k, c).transpose(1, 0, 2).reshape(-1)
    mine = ring_reduce_scatter(buf, plan.intra_axis, k).reshape(ni, c)
    c8 = ((c + 7) // 8) * 8
    if c8 != c:
        mine = jnp.zeros((ni, c8), jnp.float32).at[:, :c].set(mine)
    out, new_err = comp.compressed_reduce_scatter_sum(
        mine.reshape(-1), worker_error, plan.inter_axis)
    return out[:c], new_err


def two_level_gather_wire_bytes(shard_bytes: int, plan: HierarchyPlan):
    """Per-device wire model of ONE two-level all-gather of a
    ``shard_bytes`` shard: ``intra``/``inter`` are the actual schedule's
    per-link-class bytes; ``flat_inter`` is the slow-link bytes the FLAT
    ring all-gather of the same shard would have paid (average per
    device: every hop each device forwards one shard-sized chunk on its
    outgoing edge, ni of the n ring edges cross hosts) — the
    ``inter_uncompressed`` denominator for the stage-3 stream."""
    ni, k = plan.inter, plan.intra
    n = ni * k
    return {"intra": (k - 1) * ni * shard_bytes,
            "inter": (ni - 1) * shard_bytes,
            "flat_inter": (n - 1) * shard_bytes * ni // n}


def two_level_rs_wire_bytes(piece_bytes: int, plan: HierarchyPlan,
                            compressed: bool):
    """Per-device wire model of ONE two-level reduce-scatter of [n, c]
    fp32 pieces (``piece_bytes`` = 4c): the compressed slow hop sends
    (ni-1) sign-packed piece chunks (÷32 vs fp32) + (ni-1) scales;
    ``flat_inter`` as in `two_level_gather_wire_bytes`."""
    ni, k = plan.inter, plan.intra
    n = ni * k
    inter = (ni - 1) * (piece_bytes // 32 + 4) if compressed \
        else (ni - 1) * piece_bytes
    return {"intra": (k - 1) * ni * piece_bytes,
            "inter": inter,
            "flat_inter": (n - 1) * piece_bytes * ni // n}


def compressed_error_states(params, axis_size: int, bucket_elems: int):
    """Zero error-feedback state aligned with the bucket plan of ``params``
    (worker [padded_numel], server [padded_numel/axis] per bucket)."""
    from deepspeed_tpu.parallel import compression as comp
    leaves = jax.tree_util.tree_leaves(params)
    buckets = plan_buckets([l.shape for l in leaves], bucket_elems,
                           axis_size)
    wes, ses = [], []
    for bucket in buckets:
        pn = comp.padded_numel(bucket.padded, axis_size)
        wes.append(jnp.zeros((pn,), jnp.float32))
        ses.append(jnp.zeros((pn // axis_size,), jnp.float32))
    return wes, ses
