"""Activation checkpointing — rebuild of
deepspeed/runtime/activation_checkpointing/checkpointing.py (1,100 LoC).

The reference re-implements torch checkpointing with four extras
(config keys :759-838): partition activations across TP ranks, CPU offload
of the checkpointed activations, contiguous checkpoint buffers, and RNG
state tracking. The TPU mapping:

  checkpoint(fn)               → jax.checkpoint (rematerialization)
  partition_activations        → saved residuals carry a sharding constraint
                                 over the model axis, so each TP rank stores
                                 1/mp of every checkpoint (reference :351)
  cpu_checkpointing            → jax.checkpoint policy `offloadable`
                                 (save_and_offload_only_these_names /
                                 device→host offload of residuals)
  contiguous_memory_optimization→ XLA owns layout; accepted and ignored
  RNG tracking                 → jax threads PRNG keys functionally; nothing
                                 to restore (reference :198-349 obsolete)

`configure()` + `checkpoint()` keep the reference's module-level API so
client code ports 1:1.
"""

import functools

import jax
from jax.sharding import PartitionSpec

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.utils.logging import logger

_config = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "mesh": None,
}


def configure(mpu_=None,
              deepspeed_config=None,
              partition_activations=None,
              contiguous_checkpointing=None,
              num_checkpoints=None,
              checkpoint_in_cpu=None,
              synchronize=None,
              profile=None,
              mesh=None):
    """Module-level config (reference checkpointing.py:759)."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing_config", None)
        if ac is not None:
            _config["partition_activations"] = ac.partition_activations
            _config["contiguous_memory_optimization"] = \
                ac.contiguous_memory_optimization
            _config["cpu_checkpointing"] = ac.cpu_checkpointing
            _config["number_checkpoints"] = ac.number_checkpoints
            _config["synchronize_checkpoint_boundary"] = \
                ac.synchronize_checkpoint_boundary
            _config["profile"] = ac.profile
    for key, val in [("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile),
                     ("mesh", mesh)]:
        if val is not None:
            _config[key] = val


def is_configured():
    return True


def model_parallel_cuda_manual_seed(seed):
    """Parity no-op: JAX PRNG keys are functional; TP rng split is the
    caller folding in the axis index (reference :198 tracked CUDA rng)."""
    logger.debug(f"model_parallel_cuda_manual_seed({seed}): functional PRNG, no-op")


def _offload_policy():
    """Policy saving remat residuals to host memory (cpu_checkpointing)."""
    try:
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["ckpt"],
            offload_src="device", offload_dst="pinned_host")
    except Exception:
        # older jax: fall back to nothing-saved (pure recompute)
        return jax.checkpoint_policies.nothing_saveable


def checkpoint(function, *args, **static_kwargs):
    """Checkpoint a forward function (reference checkpointing.py:744 API:
    `checkpoint(fn, *args)` runs fn now, recomputes in backward)."""
    wrapped = checkpoint_wrapper(function, **static_kwargs)
    return wrapped(*args)


def checkpoint_wrapper(function, policy=None):
    """Return the remat-wrapped function honoring the configured mode."""
    if policy is None and _config["cpu_checkpointing"]:
        policy = _offload_policy()

    remat_fn = jax.checkpoint(function, policy=policy, prevent_cse=False) \
        if policy is not None else jax.checkpoint(function, prevent_cse=False)

    if not _config["partition_activations"]:
        return remat_fn

    mesh = _config["mesh"]

    @functools.wraps(function)
    def partitioned(*args):
        # shard the *inputs* of the checkpointed span over the model axis so
        # each TP rank stores a 1/mp slice of the boundary activation
        # (reference partition_activations :351-675); they are all-gathered
        # on recompute.
        def shard(x):
            if mesh is None or not hasattr(x, "ndim") or x.ndim < 2:
                return x
            spec = [None] * x.ndim
            # shard the sequence (second-to-last) dim when divisible
            d = x.ndim - 2
            if x.shape[d] % mesh.shape.get(mesh_lib.MODEL_AXIS, 1) == 0:
                spec[d] = mesh_lib.MODEL_AXIS
            try:
                return jax.lax.with_sharding_constraint(
                    x, jax.sharding.NamedSharding(mesh, PartitionSpec(*spec)))
            except Exception:
                return x
        args = tuple(shard(a) for a in args)
        return remat_fn(*args)

    return partitioned


class CheckpointFunction:
    """Parity alias for client code importing the autograd Function."""
    apply = staticmethod(checkpoint)
