"""LR schedules — rebuild of deepspeed/runtime/lr_schedules.py (809 LoC):
LRRangeTest (:301), OneCycle (:408), WarmupLR (:677), WarmupDecayLR (:761),
plus the CLI tuning-arg surface (:54).

TPU-native shape: each scheduler is a pure function ``step -> lr`` built from
jnp ops, so the engine evaluates it *inside* the jitted train step (traced
scalar — no per-step recompilation, no host round-trip). A torch-style
``step()/get_lr()`` mutable interface is layered on top for API parity.
"""

import argparse

import jax.numpy as jnp

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


class _Schedule:
    """Callable schedule with a torch-LR-scheduler-compatible shell."""

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def lr_at(self, step):
        raise NotImplementedError

    def __call__(self, step):
        return self.lr_at(step)

    # torch-compatible mutable interface (reference classes mirror torch)
    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [float(self.lr_at(jnp.asarray(max(self.last_batch_iteration, 0))))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_Schedule):
    """LR range test (Smith 2017) — reference lr_schedules.py:301.
    lr = min_lr * (1 + step/step_size * step_rate), continuous or staircase."""

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        x = jnp.floor(step / self.step_size) if self.staircase else step / self.step_size
        return jnp.float32(self.min_lr) * (1.0 + x * self.step_rate)


class OneCycle(_Schedule):
    """1-cycle policy — reference lr_schedules.py:408. Phase 1: min→max over
    first_step_size; phase 2: max→min over second_step_size; decay phase:
    exponential decay by decay_lr_rate per post-cycle step."""

    def __init__(self, optimizer=None, cycle_min_lr=1e-3, cycle_max_lr=1e-2,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.8, cycle_max_mom=0.9,
                 decay_mom_rate=0.0, last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = float(cycle_first_step_size)
        self.second = float(cycle_second_step_size
                            if cycle_second_step_size is not None
                            else cycle_first_step_size)
        self.decay_step_size = max(float(decay_step_size), 1.0)
        # momentum cycling retained for API parity; consumed by optimizers that
        # accept a momentum schedule (reference applies it to torch betas).
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        total = self.first + self.second
        up = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * (
            step / self.first)
        down = self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * (
            (step - self.first) / self.second)
        post = step - total
        decayed = self.cycle_min_lr * jnp.power(
            1.0 / (1.0 + self.decay_lr_rate), post / self.decay_step_size) \
            if self.decay_lr_rate > 0 else jnp.full_like(step, self.cycle_min_lr)
        lr = jnp.where(step <= self.first, up,
                       jnp.where(step <= total, down, decayed))
        return jnp.maximum(lr, 0.0)

    def mom_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        total = self.first + self.second
        down = self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * (
            step / self.first)
        up = self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * (
            (step - self.first) / self.second)
        return jnp.where(step <= self.first, down,
                         jnp.where(step <= total, up, self.cycle_max_mom))


class WarmupLR(_Schedule):
    """min→max over warmup_num_steps then constant — reference :677.
    warmup_type 'log' uses the reference's log-scaled ramp."""

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(warmup_num_steps, 2)
        self.warmup_type = warmup_type

    def _ramp(self, step):
        frac = jnp.clip(step / self.warmup_num_steps, 0.0, 1.0)
        if self.warmup_type == "log":
            # reference uses log(step+1)/log(num_steps) style ramp
            frac = jnp.log1p(jnp.minimum(step, self.warmup_num_steps)) / jnp.log(
                jnp.float32(self.warmup_num_steps + 1))
        return frac

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        gamma = self._ramp(step)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * gamma


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 at total_num_steps — reference :761."""

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000, warmup_type="log",
                 last_batch_iteration=-1):
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, warmup_type, last_batch_iteration)
        self.total_num_steps = total_num_steps

    def lr_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm = super().lr_at(step)
        decay = jnp.clip(
            (self.total_num_steps - step) /
            jnp.maximum(self.total_num_steps - self.warmup_num_steps, 1.0),
            0.0, 1.0)
        return jnp.where(step < self.warmup_num_steps, warm,
                         self.warmup_max_lr * decay)


SCHEDULES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_schedule(name, params, optimizer=None):
    if name not in SCHEDULES:
        raise ValueError(f"unknown LR schedule {name}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULES[name](optimizer=optimizer, **params)


def add_tuning_arguments(parser):
    """CLI tuning args — reference lr_schedules.py:54."""
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_momentum", type=bool, default=False)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser


def parse_arguments():
    parser = argparse.ArgumentParser()
    parser = add_tuning_arguments(parser)
    lr_sched_args, unknown_args = parser.parse_known_args()
    return lr_sched_args, unknown_args
