"""Mixed precision — rebuild of deepspeed/runtime/fp16/loss_scaler.py:56,79
and the FP16_Optimizer overflow machinery (fused_optimizer.py:17).

TPU-native stance: bf16 is the default mixed-precision mode and needs *no*
loss scaling (same exponent range as fp32). fp16 parity mode implements the
reference's dynamic loss scaler as pure jit-able state:

    scale doubles every `scale_window` overflow-free steps,
    halves (×1/scale_factor) on overflow with `hysteresis` grace,
    clamped at `min_scale`; overflowed steps skip the update
    (reference fused_optimizer.py:194-246 skip semantics).
"""

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PrecisionConfig:
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    fp16: bool = False               # fp16 parity mode (dynamic loss scale)
    static_loss_scale: float = 0     # >0 → static scale (reference loss_scale)
    initial_scale_power: int = 32
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0

    @staticmethod
    def from_ds_config(cfg):
        if cfg.fp16_enabled:
            return PrecisionConfig(compute_dtype=jnp.float16, fp16=True,
                                   static_loss_scale=cfg.loss_scale,
                                   initial_scale_power=cfg.initial_scale_power,
                                   loss_scale_window=cfg.loss_scale_window,
                                   hysteresis=cfg.hysteresis,
                                   min_loss_scale=cfg.min_loss_scale)
        if cfg.bf16_enabled:
            return PrecisionConfig(compute_dtype=jnp.bfloat16)
        return PrecisionConfig(compute_dtype=jnp.float32)

    @property
    def dynamic(self):
        return self.fp16 and not self.static_loss_scale


def init_scaler_state(cfg: PrecisionConfig) -> Dict[str, jax.Array]:
    if cfg.static_loss_scale:
        scale = float(cfg.static_loss_scale)
    elif cfg.fp16:
        scale = float(2.0 ** cfg.initial_scale_power)
    else:
        scale = 1.0
    return {
        "loss_scale": jnp.asarray(scale, jnp.float32),
        "good_steps": jnp.zeros((), jnp.int32),
        "hysteresis": jnp.asarray(cfg.hysteresis, jnp.int32),
        "overflow": jnp.zeros((), jnp.bool_),
        "skipped_steps": jnp.zeros((), jnp.int32),
    }


def grads_finite(grads) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(grads)
    finites = [jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(finites).all() if finites else jnp.asarray(True)


def update_scaler(state, cfg: PrecisionConfig, finite: jax.Array):
    """One scaler transition (reference DynamicLossScaler.update_scale,
    loss_scaler.py:79). Static mode only records the overflow bit."""
    if not cfg.dynamic:
        return {**state, "overflow": ~finite,
                "skipped_steps": state["skipped_steps"] + (~finite).astype(jnp.int32)}
    scale = state["loss_scale"]
    good = state["good_steps"]
    hyst = state["hysteresis"]

    # overflow path
    new_hyst = jnp.maximum(hyst - 1, 1)
    drop_scale = jnp.maximum(scale / 2.0, cfg.min_loss_scale)
    o_scale = jnp.where(hyst <= 1, drop_scale, scale)
    # clean path
    grow = (good + 1) >= cfg.loss_scale_window
    c_scale = jnp.where(grow, scale * 2.0, scale)
    c_good = jnp.where(grow, 0, good + 1)

    return {
        "loss_scale": jnp.where(finite, c_scale, o_scale),
        "good_steps": jnp.where(finite, c_good, 0),
        "hysteresis": jnp.where(finite, jnp.asarray(cfg.hysteresis, jnp.int32),
                                new_hyst),
        "overflow": ~finite,
        "skipped_steps": state["skipped_steps"] + (~finite).astype(jnp.int32),
    }


def cast_to_compute(tree, cfg: PrecisionConfig):
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(cfg.compute_dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)
