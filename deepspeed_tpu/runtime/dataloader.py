"""Data loading — rebuild of deepspeed/runtime/dataloader.py:10,33.

`DeepSpeedDataLoader` shards a dataset over the data-parallel axis and yields
numpy batches ready for `jax.device_put` with the engine's batch sharding.
`RepeatingLoader` is the reference's infinite wrapper, verbatim semantics.

Works with: torch Datasets/DataLoaders (torch-cpu is in-image), numpy arrays,
or any indexable. No torch import unless the dataset is a torch object.
"""

import numpy as np

from deepspeed_tpu.utils.logging import logger


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference :10)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            batch = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            batch = next(self.data_iter)
        return batch


def _to_numpy(x):
    if isinstance(x, np.ndarray):
        return x
    if hasattr(x, "numpy"):  # torch tensor
        return x.detach().cpu().numpy() if hasattr(x, "detach") else x.numpy()
    return np.asarray(x)


def default_collate(samples):
    """Stack a list of samples (each a tuple/dict/array) into a batch."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([_to_numpy(s[k]) for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([_to_numpy(s[i]) for s in samples])
                           for i in range(len(first)))
    return np.stack([_to_numpy(s) for s in samples])


class DeepSpeedDataLoader:
    """DP-sharded loader (reference :33). Each data-parallel rank sees a
    disjoint strided shard; batch order reshuffles per epoch with a seeded
    permutation so all ranks agree without communication."""

    def __init__(self,
                 dataset,
                 batch_size,
                 data_parallel_world_size=1,
                 data_parallel_rank=0,
                 collate_fn=None,
                 shuffle=True,
                 seed=1234,
                 drop_last=True,
                 local_rank=0):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.dp_world_size = int(data_parallel_world_size)
        self.dp_rank = int(data_parallel_rank)
        self.collate_fn = collate_fn or default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        try:
            self._n = len(dataset)
        except TypeError:
            raise ValueError("DeepSpeedDataLoader requires a sized dataset")
        shard = self._n // self.dp_world_size
        self.len = shard // self.batch_size
        if not drop_last and shard % self.batch_size:
            self.len += 1

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        return self.len

    def __iter__(self):
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.epoch)
            order = rng.permutation(self._n)
        else:
            order = np.arange(self._n)
        # strided DP shard, same convention as torch DistributedSampler
        my_idx = order[self.dp_rank::self.dp_world_size]
        usable = (len(my_idx) // self.batch_size) * self.batch_size
        if self.drop_last:
            my_idx = my_idx[:usable]
        for i in range(0, len(my_idx), self.batch_size):
            batch_idx = my_idx[i:i + self.batch_size]
            if len(batch_idx) < self.batch_size and self.drop_last:
                break
            samples = [self.dataset[int(j)] for j in batch_idx]
            yield self.collate_fn(samples)
        self.epoch += 1
