"""MoQ — quantize-aware training with progressive bit reduction.

Reference: runtime/quantize.py:12 `Quantizer` — every `q_period` optimizer
steps the precision of eligible (2-D) weights is reduced toward
`q_target_bits`, the period doubling after each reduction; optionally blended
with the fp32 weights (`fp16_mixed_quantize`) and with per-layer periods
modulated by Hessian eigenvalues (runtime/eigenvalue.py, engine hooks
engine.py:761-791,1199-1206,1250-1257).

TPU shape: quantization itself is the grouped Pallas kernel
(ops/pallas/quantize.py); the schedule runs at the host level between jitted
train steps — one jitted quantize-tree apply per boundary, so the hot step
function stays unchanged.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer import quantize
from deepspeed_tpu.utils.logging import logger

# number of 2-D parameters per transformer layer (reference quantize.py:9)
TWO_D_PARAMS = 6


class Quantizer:
    def __init__(self,
                 q_target_bits=8,
                 q_start_bits=16,
                 q_period=100,
                 q_offset=100,
                 q_groups=1,
                 q_mixed_fp16=False,
                 q_change_ratio=0.01,
                 q_type=0,                 # 0 symmetric / 1 asymmetric
                 q_rounding=0,             # 0 nearest / 1 stochastic
                 q_verbose=False,
                 q_eigenvalue=False,
                 use_quantizer_kernel=True,
                 layer_num=0):
        self.q_target_bits = q_target_bits
        self.layer_num = layer_num
        n = layer_num if layer_num != 0 else 1
        self.q_start_bits = [q_start_bits] * n
        self.q_period = [q_period] * n
        self.q_offset = q_offset
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.use_quantizer_kernel = use_quantizer_kernel
        self.qsteps = 0
        self.quantize_real_ratio = 1.0

    # -- schedule ---------------------------------------------------------

    def any_precision_switch(self):
        """Will the next update change any layer's precision?
        (reference quantize.py:46-56)"""
        return any(b != self.q_target_bits for b in self.q_start_bits)

    def _maybe_reduce_bits(self, index):
        """Advance layer `index`'s schedule; returns True if bits changed."""
        if self.q_start_bits[index] <= self.q_target_bits:
            return False
        if self.qsteps >= self.q_period[index]:
            self.q_start_bits[index] -= 1
            # period doubles after each reduction (reference quantize.py:118)
            self.q_period[index] = int(self.q_period[index] * 2)
            if self.q_verbose:
                logger.info(
                    f"MoQ: layer {index} → {self.q_start_bits[index]} bits "
                    f"at step {self.qsteps}, next period "
                    f"{self.q_period[index]}")
            return True
        return False

    def update_fp16_ratio(self):
        """Decay the fp32-blend toward pure quantized weights
        (reference quantize.py:236-241)."""
        if self.q_mixed_fp16 and self.quantize_real_ratio > 0:
            self.quantize_real_ratio = max(
                0.0, self.quantize_real_ratio - self.q_change_ratio)

    def eigenvalue_adjust(self, eigenvalues):
        """Scale per-layer periods by normalized eigenvalues: flatter layers
        (small curvature) quantize sooner (reference quantize.py engine hook
        engine.py:1250-1257)."""
        if not eigenvalues:
            return
        ev = [max(float(e), 1e-12) for e in eigenvalues]
        mean = sum(ev) / len(ev)
        for i in range(min(self.layer_num or 1, len(ev))):
            factor = ev[i] / mean
            self.q_period[i] = max(1, int(self.q_period[i] * factor))

    # -- application ------------------------------------------------------

    def _layer_index(self, path_names):
        """Map a param path to a layer index for per-layer schedules."""
        if self.layer_num == 0:
            return 0
        for name in path_names:
            for tok in name.replace("_", ".").split("."):
                if tok.isdigit():
                    return min(int(tok), self.layer_num - 1)
        return 0

    def quantize_tree(self, params, overflow=False, eigenvalues=None,
                      key: Optional[jax.Array] = None):
        """One MoQ boundary: advance the schedule and return the params tree
        with every 2-D weight fake-quantized at its layer's current bits.
        Mirrors reference quantize.py:58-135 `quantize`."""
        if overflow and not self.q_mixed_fp16:
            # overflow steps consume no schedule budget (reference
            # quantize.py:64-66 returns before stepping the counter)
            return params
        self.qsteps += TWO_D_PARAMS * (self.layer_num if self.layer_num else 1)
        if self.q_eigenvalue and eigenvalues:
            self.eigenvalue_adjust(eigenvalues)
        for i in range(len(self.q_start_bits)):
            self._maybe_reduce_bits(i)
        self.update_fp16_ratio()

        stochastic = self.q_rounding == 1
        sym = self.q_type == 0
        keys = {}

        def quant_leaf(path, leaf):
            arr = jnp.asarray(leaf)
            if arr.ndim != 2 or not jnp.issubdtype(arr.dtype, jnp.floating):
                return leaf
            idx = self._layer_index(
                [str(getattr(k, "key", k)) for k in path])
            bits = self.q_start_bits[idx]
            if bits >= 16:
                return leaf
            groups = self.q_groups if arr.size % self.q_groups == 0 else 1
            sub = jax.random.fold_in(key, len(keys)) if key is not None \
                else None
            keys[len(keys)] = True
            q = quantize(arr, bits=bits, groups=groups, sym=sym,
                         stochastic=stochastic, key=sub)
            if self.q_mixed_fp16 and self.quantize_real_ratio > 0:
                r = self.quantize_real_ratio
                q = r * arr + (1.0 - r) * q
            return q.astype(arr.dtype)

        return jax.tree_util.tree_map_with_path(quant_leaf, params)
