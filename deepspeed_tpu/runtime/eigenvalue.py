"""Hessian max-eigenvalue estimation by power iteration.

Reference: runtime/eigenvalue.py:7 `Eigenvalue` — per-layer power iteration
on the loss curvature, used to modulate MoQ quantization periods
(engine.py:1250-1257: layers with small curvature quantize earlier).

The torch version does a double-backward through retained graphs; in JAX a
Hessian-vector product is just `jvp` of `grad` — no graph bookkeeping, and
the whole iteration jits. Eigenvalues are computed per top-level param block
(the "layer" granularity the reference gets from module traversal).
"""

from typing import Callable, Dict, List, Tuple

import numpy as np
import jax
import jax.numpy as jnp


def _normalize(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    norm = jnp.sqrt(sum(jnp.vdot(l, l).real for l in leaves))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree_util.tree_map(lambda l: l / norm, tree), norm


class Eigenvalue:
    def __init__(self,
                 verbose=False,
                 max_iter=100,
                 tol=1e-2,
                 stability=1e-6,
                 gas_boundary_resolution=1,
                 layer_name="",
                 layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def hvp(self, loss_fn: Callable, params, vec):
        """Hessian-vector product: d/dε grad(params + ε·vec) — jvp of grad."""
        grad_fn = jax.grad(loss_fn)
        _, hv = jax.jvp(grad_fn, (params,), (vec,))
        return hv

    def _power_iterate(self, hvp_fn, params, v):
        """Shared power-iteration loop (reference eigenvalue.py:45-110:
        random init, normalize, iterate until |Δλ|/λ < tol or max_iter).
        `hvp_fn(params, v)` must already be jitted by the caller so the
        compile happens once for all blocks and iterations."""
        v, _ = _normalize(v)
        eig = 0.0
        for _ in range(self.max_iter):
            hv = hvp_fn(params, v)
            hv = jax.tree_util.tree_map(
                lambda l: jnp.nan_to_num(l, nan=0.0, posinf=0.0, neginf=0.0),
                hv)
            v, norm = _normalize(hv)
            new_eig = float(norm)
            if eig > 0 and abs(new_eig - eig) / max(eig, 1e-12) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return eig + self.stability

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           rng=None) -> float:
        """Dominant Hessian eigenvalue of loss_fn at params."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef,
            [jax.random.normal(k, l.shape, jnp.float32)
             for k, l in zip(keys, leaves)])
        hvp_fn = jax.jit(lambda p, vv: self.hvp(loss_fn, p, vv))
        return self._power_iterate(hvp_fn, params, v)

    def find_layer_blocks(self, params) -> List[Tuple[str, list]]:
        """Locate per-transformer-layer param subtrees, numerically ordered —
        the role the reference's `layer_name` module lookup plays
        (eigenvalue.py:112-130). Walks the tree for the dict with the most
        children whose names end in a layer index (encoder layers in this
        repo's models: 'DeepSpeedTransformerLayer_3', HF: '3', GPT-2:
        'h_3'). Returns [(name, key_path)] sorted by index."""
        def layer_idx(name):
            tail = name.rsplit("_", 1)[-1] if "_" in name else name
            return int(tail) if tail.isdigit() else None

        best: Tuple[list, Dict[int, str]] = ([], {})
        stack = [(params, [])]
        while stack:
            node, path = stack.pop()
            if not isinstance(node, dict):
                continue
            idxmap = {}
            for k in node.keys():
                i = layer_idx(str(k))
                if i is not None:
                    idxmap[i] = k
            if len(idxmap) > len(best[1]):
                best = (path, idxmap)
            for k, v in node.items():
                stack.append((v, path + [k]))
        path, idxmap = best
        return [(idxmap[i], path + [idxmap[i]]) for i in sorted(idxmap)]

    def compute_layer_eigenvalues(self, loss_fn: Callable, params,
                                  rng=None) -> List[float]:
        """Per-transformer-layer eigenvalues, index-aligned with the MoQ
        quantizer's per-layer schedules (Quantizer.eigenvalue_adjust).

        One jitted HVP over the FULL params is compiled once and reused for
        every block and iteration; restricting the probe vector's support to
        one layer block power-iterates that block of the Hessian."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        blocks = self.find_layer_blocks(params)
        hvp_fn = jax.jit(lambda p, vv: self.hvp(loss_fn, p, vv))

        def get(tree, key_path):
            for k in key_path:
                tree = tree[k]
            return tree

        zeros = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, jnp.float32), params)

        if not blocks:
            return [self.compute_eigenvalue(loss_fn, params, rng)]

        results = []
        for i, (name, key_path) in enumerate(blocks):
            sub = get(params, key_path)
            krng = jax.random.fold_in(rng, i)
            leaves, treedef = jax.tree_util.tree_flatten(sub)
            keys = jax.random.split(krng, len(leaves))
            v_blk = jax.tree_util.tree_unflatten(
                treedef,
                [jax.random.normal(k, l.shape, jnp.float32)
                 for k, l in zip(keys, leaves)])

            def embed(blk):
                def swap(path, z):
                    names = [str(getattr(k, "key", k)) for k in path]
                    if names[:len(key_path)] == [str(k) for k in key_path]:
                        b = blk
                        for k in path[len(key_path):]:
                            b = b[getattr(k, "key", k)]
                        return b
                    return z
                return jax.tree_util.tree_map_with_path(swap, zeros)

            restrict = lambda tree: get(tree, key_path)  # noqa: E731
            hvp_blk = lambda p, vb: restrict(hvp_fn(p, embed(vb)))  # noqa
            results.append(self._power_iterate(hvp_blk, params, v_blk))
        return results

    # reference API aliases ------------------------------------------------
    def nan_to_num(self, x):
        return jnp.nan_to_num(jnp.asarray(x), nan=0.0, posinf=0.0,
                              neginf=0.0)

    def normalize(self, v):
        return _normalize(v)[0]
