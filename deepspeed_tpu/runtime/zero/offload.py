"""ZeRO-Offload / ZeRO-Infinity runner — the host side of the optimizer step.

Rebuild of the reference's offload architecture (stage2.py:747-925 CPU grad
path + DeepSpeedCPUAdam + swap_tensor/): the accelerator computes
loss+gradients in compute dtype; fp32 master params and Adam moments live in
host DRAM (device="cpu") or NVMe (device="nvme", via the native aio
swapper); the optimizer step runs in the native SIMD library
(csrc/cpu_adam.cpp); updated params are pushed back to the device in
compute dtype.

This trades step latency for HBM: params/grads on device are compute-dtype
only, optimizer state consumes zero HBM — the reference's "13B on one
V100" recipe (SURVEY §6).
"""

from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.utils.logging import logger


class HostOffloadOptimizer:
    """Holds fp32 master state on host; applies native Adam per leaf."""

    def __init__(self, params_device, optimizer, offload_cfg, aio_cfg=None):
        # host steps exist for Adam/AdamW (SIMD ds_adam_step) and LAMB
        # (ds_lamb_step); anything else would silently train with the wrong
        # algorithm (the reference restricts offload to DeepSpeedCPUAdam,
        # stage2.py:747 — LAMB offload is a TPU-side extension)
        from deepspeed_tpu.ops.adam import FusedAdam
        from deepspeed_tpu.ops.lamb import FusedLamb
        if not isinstance(optimizer, (FusedAdam, FusedLamb)):
            raise ValueError(
                f"optimizer offload supports Adam/AdamW/LAMB optimizers "
                f"only, got {type(optimizer).__name__}")
        self.is_lamb = isinstance(optimizer, FusedLamb)
        self.optimizer = optimizer
        if getattr(optimizer, "moment_dtype", "fp32") != "fp32":
            # the SIMD step and the swapper both run on fp32 host arrays;
            # half-storage moments are a device-optimizer feature
            logger.warning(
                "moment_dtype=%s ignored by the host offload tier: offloaded "
                "moments are stored fp32 in host DRAM/NVMe",
                optimizer.moment_dtype)
        self.device_nvme = offload_cfg.device == C.OFFLOAD_NVME_DEVICE
        self.step_count = 0

        leaves, self.treedef = jax.tree_util.tree_flatten(
            jax.device_get(params_device))
        self.master: List[np.ndarray] = [
            np.ascontiguousarray(np.asarray(l, np.float32)) for l in leaves]

        self._native = None
        try:
            from deepspeed_tpu.ops.native import cpu_adam as native_cpu_adam
            self._native = native_cpu_adam.load()
        except Exception as e:
            logger.warning(f"native cpu_adam unavailable ({e}); "
                           f"using numpy fallback")

        self.swapper = None
        if self.device_nvme:
            from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper
            assert offload_cfg.nvme_path, "offload to nvme requires nvme_path"
            # pipeline_write: moment stores run write-behind on a dedicated
            # aio handle, overlapping the next leaves' SIMD steps (the
            # reference's PipelinedOptimizerSwapper write leg)
            self.swapper = OptimizerStateSwapper(
                offload_cfg.nvme_path, aio_cfg,
                pipeline_write=getattr(offload_cfg, "pipeline_write", False),
                buffer_count=getattr(offload_cfg, "buffer_count", 2))
            for i, m in enumerate(self.master):
                self.swapper.init_state(i, m.shape)
            self.m = self.v = None
        else:
            self.m = [np.zeros_like(x) for x in self.master]
            self.v = [np.zeros_like(x) for x in self.master]
        self._bf16_out = None   # per-leaf uint16 staging for the device push
        self._lamb_buf = None   # per-leaf fp32 scratch for LAMB's update

    def _hyper(self):
        opt = self.optimizer
        betas = getattr(opt, "betas", (0.9, 0.999))
        return dict(beta1=betas[0], beta2=betas[1],
                    eps=getattr(opt, "eps", 1e-8),
                    weight_decay=getattr(opt, "weight_decay", 0.0),
                    adamw_mode=getattr(opt, "adam_w_mode", True),
                    bias_correction=getattr(opt, "bias_correction", True),
                    max_coeff=getattr(opt, "max_coeff", 10.0),
                    min_coeff=getattr(opt, "min_coeff", 0.01))

    def _apply_leaf(self, p, g, m, v, lr, hyper):
        if self.is_lamb:
            return self._apply_leaf_lamb(p, g, m, v, lr, hyper)
        if self._native is not None:
            self._native.adam_step(p.reshape(-1), np.ascontiguousarray(
                g.reshape(-1)), m.reshape(-1), v.reshape(-1),
                self.step_count, lr, hyper["beta1"], hyper["beta2"],
                hyper["eps"], hyper["weight_decay"], hyper["adamw_mode"],
                hyper["bias_correction"])
            return
        beta1, beta2 = hyper["beta1"], hyper["beta2"]
        bc1 = 1 - beta1 ** self.step_count if hyper["bias_correction"] else 1.0
        bc2 = 1 - beta2 ** self.step_count if hyper["bias_correction"] else 1.0
        if hyper["weight_decay"] and not hyper["adamw_mode"]:
            g = g + hyper["weight_decay"] * p
        m *= beta1
        m += (1 - beta1) * g
        v *= beta2
        v += (1 - beta2) * g * g
        update = (m / bc1) / (np.sqrt(v / bc2) + hyper["eps"])
        if hyper["weight_decay"] and hyper["adamw_mode"]:
            update = update + hyper["weight_decay"] * p
        p -= lr * update

    def _apply_leaf_lamb(self, p, g, m, v, lr, hyper):
        g = np.ascontiguousarray(g.reshape(-1), dtype=np.float32)
        pf, mf, vf = p.reshape(-1), m.reshape(-1), v.reshape(-1)
        if self._native is not None:
            self._native.lamb_step(
                pf, g, mf, vf, self.step_count, lr, hyper["beta1"],
                hyper["beta2"], hyper["eps"], hyper["weight_decay"],
                hyper["max_coeff"], hyper["min_coeff"],
                hyper["bias_correction"])
            return
        beta1, beta2 = hyper["beta1"], hyper["beta2"]
        bc1 = 1 - beta1 ** self.step_count if hyper["bias_correction"] else 1.0
        bc2 = 1 - beta2 ** self.step_count if hyper["bias_correction"] else 1.0
        mf *= beta1
        mf += (1 - beta1) * g
        vf *= beta2
        vf += (1 - beta2) * g * g
        update = (mf / bc1) / (np.sqrt(vf / bc2) + hyper["eps"])
        if hyper["weight_decay"]:
            update += hyper["weight_decay"] * pf
        p_norm = float(np.linalg.norm(pf))
        u_norm = float(np.linalg.norm(update))
        trust = 1.0
        if p_norm > 0 and u_norm > 0:
            trust = np.clip(p_norm / max(u_norm, 1e-12),
                            hyper["min_coeff"], hyper["max_coeff"])
        pf -= lr * trust * update

    def step_streamed(self, grad_leaves, lr: float, grad_scale: float = 1.0,
                      push_fn=None, out_dtype=None):
        """Pipelined offload step — the overlap architecture of the
        reference's pipelined swapper + tiled param copies
        (swap_tensor/pipelined_optimizer_swapper.py:60,
        csrc/adam/cpu_adam.cpp:67-120), built on JAX async transfers:

        1. every gradient leaf starts its d2h copy up front
           (`copy_to_host_async`) so transfers stream while earlier leaves
           run their SIMD step;
        2. each leaf steps as it arrives — one single-pass native call
           (wire-dtype grads, ``grad_scale`` folded into the read, bf16
           push copy written in the same pass);
        3. ``push_fn(i, host_array)`` dispatches the h2d put immediately
           (JAX device puts are async), overlapping the remaining steps;
           on the NVMe tier, leaf i+1's moments prefetch while leaf i
           steps, as in `step`.

        Returns the list of push_fn results (None entries without one).
        """
        import ml_dtypes

        self.step_count += 1
        hyper = self._hyper()
        n = len(self.master)
        assert len(grad_leaves) == n, (len(grad_leaves), n)
        for g in grad_leaves:
            if hasattr(g, "copy_to_host_async"):
                try:
                    g.copy_to_host_async()
                except Exception:
                    pass  # backend without async host copies: asarray blocks
        want_bf16_out = (
            push_fn is not None and out_dtype is not None
            and np.dtype(out_dtype) == np.dtype(ml_dtypes.bfloat16)
            and self._native is not None)
        if want_bf16_out and self._bf16_out is None:
            self._bf16_out = [np.empty(p.shape, np.uint16)
                              for p in self.master]
        outs = []
        if self.swapper is not None and n > 0:
            self.swapper.prefetch(0)
        for i in range(n):
            g_np = np.ascontiguousarray(np.asarray(grad_leaves[i]))
            if g_np.dtype == np.float16:
                g_np = g_np.astype(np.float32)
            p = self.master[i]
            if self.swapper is not None:
                m, v = self.swapper.fetch(i)
                if i + 1 < n:
                    self.swapper.prefetch(i + 1)
            else:
                m, v = self.m[i], self.v[i]
            bf16_buf = self._bf16_out[i].reshape(-1) if want_bf16_out else None
            if self._native is not None:
                if self.is_lamb:
                    if self._lamb_buf is None or self._lamb_buf.size < p.size:
                        self._lamb_buf = np.empty(p.size, np.float32)
                    self._native.lamb_step_ex(
                        p.reshape(-1), g_np.reshape(-1), m.reshape(-1),
                        v.reshape(-1), self.step_count, lr,
                        hyper["beta1"], hyper["beta2"], hyper["eps"],
                        hyper["weight_decay"], hyper["max_coeff"],
                        hyper["min_coeff"], hyper["bias_correction"],
                        grad_scale=grad_scale, params_bf16=bf16_buf,
                        update_buf=self._lamb_buf[:p.size])
                else:
                    self._native.adam_step_ex(
                        p.reshape(-1), g_np.reshape(-1), m.reshape(-1),
                        v.reshape(-1), self.step_count, lr,
                        hyper["beta1"], hyper["beta2"], hyper["eps"],
                        hyper["weight_decay"], hyper["adamw_mode"],
                        hyper["bias_correction"], grad_scale=grad_scale,
                        params_bf16=bf16_buf)
            else:
                g32 = np.asarray(g_np, np.float32)
                if grad_scale != 1.0:
                    g32 = g32 * np.float32(grad_scale)
                self._apply_leaf(p, g32, m, v, lr, hyper)
            if self.swapper is not None:
                self.swapper.store(i, m, v)
            if push_fn is None:
                outs.append(None)
                continue
            if bf16_buf is not None:
                host_out = self._bf16_out[i].view(ml_dtypes.bfloat16)
            elif out_dtype is not None \
                    and np.dtype(out_dtype) != np.float32:
                host_out = p.astype(out_dtype)
            else:
                host_out = p
            outs.append(push_fn(i, host_out))
        return outs

    def step(self, grads_np: List[np.ndarray], lr: float):
        self.step_count += 1
        hyper = self._hyper()
        n = len(self.master)
        if self.swapper is None and self._native is not None \
                and not self.is_lamb:
            # CPU tier, Adam: one multi-tensor native call (OpenMP spans the
            # whole leaf list — reference multi_tensor_apply)
            grads = [np.ascontiguousarray(np.asarray(g, np.float32)
                                          .reshape(-1)) for g in grads_np]
            self._native.adam_step_multi(
                [p.reshape(-1) for p in self.master], grads,
                [m.reshape(-1) for m in self.m],
                [v.reshape(-1) for v in self.v],
                self.step_count, lr, hyper["beta1"], hyper["beta2"],
                hyper["eps"], hyper["weight_decay"], hyper["adamw_mode"],
                hyper["bias_correction"])
            return self.master
        if self.swapper is not None and n > 0:
            self.swapper.prefetch(0)
        for i in range(n):
            g = np.asarray(grads_np[i], np.float32)
            p = self.master[i]
            if self.swapper is not None:
                m, v = self.swapper.fetch(i)
                if i + 1 < n:
                    # double buffering: next leaf's moments stream from NVMe
                    # while this leaf runs the SIMD Adam step
                    self.swapper.prefetch(i + 1)
            else:
                m, v = self.m[i], self.v[i]
            self._apply_leaf(p, g, m, v, lr, hyper)
            if self.swapper is not None:
                self.swapper.store(i, m, v)
        return self.master

    def params_tree(self):
        return jax.tree_util.tree_unflatten(self.treedef, self.master)

    def state_dict(self):
        if self.swapper is not None:
            moments = [self.swapper.fetch(i) for i in range(len(self.master))]
            m = [a for a, _ in moments]
            v = [b for _, b in moments]
        else:
            m, v = self.m, self.v
        return {
            "step": self.step_count,
            "exp_avg": jax.tree_util.tree_unflatten(self.treedef, m),
            "exp_avg_sq": jax.tree_util.tree_unflatten(self.treedef, v),
        }

    def load_state_dict(self, sd):
        self.step_count = int(np.asarray(sd["step"]))
        m = jax.tree_util.tree_leaves(sd["exp_avg"])
        v = jax.tree_util.tree_leaves(sd["exp_avg_sq"])
        for i in range(len(self.master)):
            mi = np.ascontiguousarray(np.asarray(m[i], np.float32))
            vi = np.ascontiguousarray(np.asarray(v[i], np.float32))
            if self.swapper is not None:
                self.swapper.store(i, mi, vi)
            else:
                self.m[i], self.v[i] = mi, vi
