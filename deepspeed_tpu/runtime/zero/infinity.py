"""ZeRO-Infinity for one TPU chip: segment-streamed training of models
whose parameters + optimizer state exceed HBM by an order of magnitude.

Reference role: deepspeed/runtime/zero/stage3.py +
swap_tensor/partitioned_param_swapper.py:36 + the ZeRO-Infinity paper's
claim lattice (docs/_posts/2021-03-08-zero3-offload.md:51 — 40B params
on one 32 GB V100). The reference streams params from NVMe/DRAM through
module fetch/release hooks around every submodule and runs the optimizer
on host cores. The TPU-native realization keeps every FLOP on the chip
and expresses the tiers as XLA memory spaces:

- **fp32 master + Adam moments rest in ``pinned_host``** (device-host
  DRAM, tens of GB), never all resident in HBM — same placement as the
  r4 streamed-offload tier (zero/offload_stream.py).
- **Compute params are materialized PER SEGMENT**: the [n_layer, ...]
  scan-stacked transformer splits into K row-segments; one jitted
  fetch casts a segment's pinned fp32 rows to a bf16 stack in HBM, the
  segment's forward runs, and the stack is freed before the next
  segment fetch. Peak param HBM = one segment, not the model.
- **Backward re-fetches each segment in reverse** (boundary activations
  were kept — K+1 small [B,S,E] tensors), computes the segment vjp
  with rematerialized block bodies, streams the PER-ROW Adam update
  (donated pinned m/v/master in, updated out) and frees the segment's
  grads before touching the previous segment.
- **The compute-dtype parameters rest on client NVMe** via
  PartitionedParamSwapper files: written at init (from the host-side
  init, no d2h) and refreshed on ``park_to_nvme()``/checkpoint. Cold
  start restores the pinned masters FROM the files
  (``restore_from_nvme``), which is the disk-read path at full scale.
  On disaggregated deployments (this target: device->client moves at
  ~10 MB/s through the tunnel) a per-step disk round-trip of multi-GB
  params is physically impossible for any framework, so per-step disk
  parking is gated by ``park_threshold_bytes`` — small models keep the
  r4 park-every-step behavior, large models park on demand — and the
  step streams through the pinned tier instead.

HBM peak per step ~= segment bf16 params + segment bf16 grads + one
segment's fp32 master rows + boundary activations + remat workspace —
for a 6.2B-param GPT-2 (E=4096, 30 layers) in 6 segments that is ~9 GB
on a 16 GB chip, against 12.4 GB of bf16 params and 61 GB of state.

Supports GPT2LMHeadModel configs with ``scan_layers=True`` and tied
embeddings (the flagship family). Select via the engine config::

    "zero_optimization": {"stage": 3,
        "offload_param": {"device": "nvme", "nvme_path": ...,
                          "stream_segments": 6},
        "offload_optimizer": {"device": "cpu"}}
"""

import os
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.utils.logging import logger


def gpt2_client_init(cfg, seed=0):
    """Client-side parameter init WITHOUT materializing the model on any
    device: structure from ``jax.eval_shape``, values from numpy
    (kernels ~ N(0, 1/sqrt(fan_in)), embeddings N(0, .02/.01), LN
    ones/zeros). This is how multi-GB models enter the streamed engine —
    ``model.init`` would build the whole tree through the device."""
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    model = GPT2LMHeadModel(cfg)
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        np.zeros((1, 8), np.int32))["params"]
    rs = np.random.RandomState(seed)

    def leaf(path, s):
        names = [str(getattr(p, "key", p)) for p in path]
        last = names[-1]
        if last == "kernel":
            a = rs.standard_normal(s.shape).astype(np.float32) \
                / np.sqrt(s.shape[-2])
        elif last == "wte":
            a = rs.standard_normal(s.shape).astype(np.float32) * 0.02
        elif last == "wpe":
            a = rs.standard_normal(s.shape).astype(np.float32) * 0.01
        elif last == "scale":
            a = np.ones(s.shape, np.float32)
        else:
            a = np.zeros(s.shape, np.float32)
        # STAY numpy (ml_dtypes handles bf16): jnp.asarray here would
        # materialize every leaf on the default device — and on a
        # disaggregated target, reading it back for the NVMe files
        # crosses the ~10 MB/s d2h tunnel
        return a.astype(np.dtype(s.dtype))
    return jax.tree_util.tree_map_with_path(leaf, shapes)


class _Segment(nn.Module):
    """``rows`` scanned transformer blocks — the streamed unit. Param
    tree matches GPT2LMHeadModel's ``h/blk`` subtree with a [rows, ...]
    leading axis, so segment params are row-slices of the full stacks."""
    config: object
    rows: int

    @nn.compact
    def __call__(self, x):
        from deepspeed_tpu.models.gpt2 import ScanBody
        scanned = nn.scan(ScanBody,
                          variable_axes={"params": 0},
                          split_rngs={"params": True},
                          in_axes=(nn.broadcast, nn.broadcast),
                          length=self.rows)
        x, _ = scanned(self.config, name="h")(x, True, 1.0)
        return x


class InfinityEngine:
    """Segment-streamed ZeRO-Infinity trainer for scan-stacked GPT-2.

    ``train_batch({"input_ids": ..., "labels":?}) -> loss`` like the
    main engine; params/optimizer state live in pinned_host + NVMe as
    described in the module docstring.
    """

    def __init__(self, model_cfg, params, device=None, *,
                 segments: int = 4,
                 nvme_path: Optional[str] = None,
                 lr: float = 1e-4, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w: bool = True,
                 moment_dtype=jnp.bfloat16,
                 park_threshold_bytes: int = 256 * 1024 * 1024,
                 lr_fn=None, restore_params: bool = False):
        cfg = model_cfg
        assert cfg.scan_layers and cfg.tie_word_embeddings, \
            "InfinityEngine streams the scan-stacked tied-embedding family"
        assert cfg.n_layer % segments == 0, (cfg.n_layer, segments)
        self.cfg = cfg
        self.K = segments
        self.rows = cfg.n_layer // segments
        self.lr, self.betas, self.eps = lr, betas, eps
        self.wd, self.adam_w = weight_decay, adam_w
        self.lr_fn = lr_fn
        self._mdtype = moment_dtype
        self.step_count = 0
        self.device = device or jax.devices()[0]
        self.mesh = Mesh(np.array([self.device]), ("d",))
        kinds = {m.kind for m in self.device.addressable_memories()}
        # CPU advertises host memory kinds but cannot lower the placement
        # annotation — the tiers only separate on real accelerators
        self._host_kind = "pinned_host" \
            if "pinned_host" in kinds and self.device.platform != "cpu" \
            else None
        self._dev_sh = self._sh("device")
        self._host_sh = self._sh(self._host_kind)

        # ---- state layout: per-layer-ROW pinned fp32 master + moments
        # (the update's streaming unit; one row of a 6B model is ~800 MB
        # of fp32 master — comfortably double-bufferable)
        blk = params["h"]["blk"]
        self._blk_leaves, self._blk_def = jax.tree_util.tree_flatten(blk)
        self._blk_shapes = [tuple(l.shape) for l in self._blk_leaves]
        emb = {k: params[k] for k in ("wte", "wpe", "ln_f")}
        self._emb_leaves, self._emb_def = jax.tree_util.tree_flatten(emb)

        # host placement via in-body device_put, NOT out_shardings: the
        # AOT compile path rejects host-memory entry outputs declared
        # through out_shardings ("layout for this output is not set to
        # host memory"), while the device_put form is the r4-proven one.
        # Placement is BATCHED over ROW-CHUNKS of each stacked leaf
        # (~1 GiB of rows per jit call, split into pinned rows inside
        # the jit): per-ROW placement was 13 x n_layer dispatches whose
        # per-call tunnel latency dominated (~500 s of a 640 s setup at
        # 9.4B), while one-jit-per-WHOLE-leaf crashed the remote AOT
        # compile helper at multi-GB leaf stacks (HTTP 500) — chunking
        # keeps both failure modes out.
        place_fns = {}

        def place_chunk(chunk):
            key = chunk.shape
            f = place_fns.get(key)
            if f is None:
                def body(x):
                    xf = x.astype(jnp.float32)
                    rows = tuple(jax.device_put(xf[r], self._host_sh)
                                 for r in range(x.shape[0]))
                    zm = tuple(jax.device_put(
                        jnp.zeros(x.shape[1:], self._mdtype),
                        self._host_sh) for _ in range(x.shape[0]))
                    zv = tuple(jax.device_put(
                        jnp.zeros(x.shape[1:], jnp.float32),
                        self._host_sh) for _ in range(x.shape[0]))
                    return rows, zm, zv
                f = place_fns[key] = jax.jit(body)
            return f(chunk)

        self.master: List[List] = [[None] * len(self._blk_leaves)
                                   for _ in range(cfg.n_layer)]
        self.m: List[List] = [[None] * len(self._blk_leaves)
                              for _ in range(cfg.n_layer)]
        self.v: List[List] = [[None] * len(self._blk_leaves)
                              for _ in range(cfg.n_layer)]
        for i, leaf in enumerate(self._blk_leaves):
            arr = np.asarray(leaf)
            # budget against the IN-JIT footprint (fp32 master rows +
            # fp32/bf16 zero moments ≈ 5x the bf16 source bytes), not
            # the source bytes — the AOT helper's multi-GB-per-program
            # crash is what chunking exists to avoid
            row_bytes = max(arr[0].size * 10, 1)
            step = max(1, int((1 << 30) // row_bytes))
            for s in range(0, cfg.n_layer, step):
                rows, zm, zv = place_chunk(arr[s:s + step])
                for j, r in enumerate(range(s, min(s + step,
                                                   cfg.n_layer))):
                    self.master[r][i] = rows[j]
                    self.m[r][i] = zm[j]
                    self.v[r][i] = zv[j]
        place_row = jax.jit(
            lambda *ls: tuple(
                jax.device_put(jnp.asarray(l).astype(jnp.float32),
                               self._host_sh) for l in ls))
        zeros_row = jax.jit(
            lambda *ls: tuple(
                jax.device_put(x, self._host_sh) for l in ls
                for x in (jnp.zeros(l.shape, self._mdtype),
                          jnp.zeros(l.shape, jnp.float32))))
        self.emb_master = list(place_row(*[np.asarray(l)
                                           for l in self._emb_leaves]))
        emz = zeros_row(*self.emb_master)
        self.emb_m, self.emb_v = list(emz[0::2]), list(emz[1::2])

        # ---- NVMe at-rest tier
        self._fns = {}            # jit cache (restore uses place_row)
        self._swapper = None
        self._park_threshold = park_threshold_bytes
        self.param_bytes = sum(
            int(np.prod(s)) * jnp.dtype(cfg.param_dtype).itemsize
            for s in self._blk_shapes) + sum(
            int(np.prod(l.shape)) * jnp.dtype(cfg.param_dtype).itemsize
            for l in self._emb_leaves)
        if nvme_path:
            from deepspeed_tpu.runtime.swap_tensor import (
                PartitionedParamSwapper)
            # DURABLE at-rest tier: stable sub-dir + meta sidecar, no
            # pid scoping, survives the process — a fresh engine with
            # restore_params=True cold-starts from these files.
            # CONTRACT: nvme_path identifies ONE training run's at-rest
            # state (like a checkpoint dir) — two engines sharing it
            # overwrite each other; call release() to reclaim the disk
            self._swapper = PartitionedParamSwapper(
                nvme_path, sub_dir="infinity_params", durable=True)
            if restore_params:
                self._swapper.load_meta()
                self.restore_from_nvme()
            else:
                # written host-side (numpy in, no d2h) — params rest on
                # disk from step zero
                self._swapper.write_all(
                    [np.asarray(l).astype(self._np_pdtype())
                     for l in self._emb_leaves] +
                    [np.asarray(l).astype(self._np_pdtype())
                     for l in self._blk_leaves])

        logger.info(
            f"InfinityEngine: {cfg.n_layer} layers in {segments} segments "
            f"of {self.rows}; {self.param_bytes / 2**30:.2f} GiB compute "
            f"params, master+moments in "
            f"{self._host_kind or 'device memory'}; NVMe at-rest tier "
            f"{'ON' if self._swapper else 'off'}")

    # ------------------------------------------------------------- helpers
    def _np_pdtype(self):
        return np.dtype(jnp.dtype(self.cfg.param_dtype).name) \
            if jnp.dtype(self.cfg.param_dtype) != jnp.bfloat16 \
            else jnp.bfloat16

    def _sh(self, kind):
        sh = NamedSharding(self.mesh, PartitionSpec())
        if kind and kind != "device":
            sh = sh.with_memory_kind(kind)
        return sh

    def _seg_apply(self, seg_params, x):
        mod = _Segment(self.cfg, self.rows)
        return mod.apply({"params": {"h": {"blk": jax.tree_util.
                                           tree_unflatten(self._blk_def,
                                                          seg_params)}}}, x)

    # ------------------------------------------------ jitted building blocks
    def _fn(self, name, build):
        f = self._fns.get(name)
        if f is None:
            f = self._fns[name] = build()
        return f

    def _fetch_seg(self, seg):
        """pinned fp32 rows -> one [rows, ...] bf16 stack per leaf (HBM)
        and the fp32 row list (HBM) for the update."""
        rows = list(range(seg * self.rows, (seg + 1) * self.rows))

        def build():
            nleaf = len(self._blk_leaves)
            cdt = self.cfg.param_dtype

            def fetch(*flat):
                # flat: rows-major [row0 leaves..., row1 leaves...]
                per_leaf = []
                for i in range(nleaf):
                    per_leaf.append(jnp.stack(
                        [jax.device_put(flat[r * nleaf + i], self._dev_sh)
                         for r in range(self.rows)]).astype(cdt))
                return tuple(per_leaf)
            return jax.jit(fetch)
        fetch = self._fn("fetch_seg", build)
        flat = [m for r in rows for m in self.master[r]]
        return list(fetch(*flat))

    def _embed_fwd(self):
        cfg = self.cfg

        def build():
            def f(wte, wpe, ids):
                from deepspeed_tpu.models.gpt2 import _embed_lookup
                wte_c = wte.astype(cfg.dtype)
                x = _embed_lookup(wte_c, ids) \
                    + wpe[:ids.shape[1]].astype(cfg.dtype)[None]
                return x
            return jax.jit(f)
        return self._fn("embed_fwd", build)

    def _seg_fwd(self):
        def build():
            return jax.jit(lambda ps, x: self._seg_apply(list(ps), x))
        return self._fn("seg_fwd", build)

    def _seg_grad(self):
        def build():
            def g(ps, x, dy):
                _, vjp = jax.vjp(
                    lambda p, xx: self._seg_apply(list(p), xx),
                    tuple(ps), x)
                dps, dx = vjp(dy)
                return tuple(dps), dx
            return jax.jit(g)
        return self._fn("seg_grad", build)

    def _head_grad(self):
        cfg = self.cfg

        def build():
            def loss_fn(lnf_scale, lnf_bias, wte, x, labels):
                from deepspeed_tpu.models.gpt2 import chunked_lm_loss, \
                    lm_loss
                xf = x.astype(jnp.float32)
                mu = jnp.mean(xf, axis=-1, keepdims=True)
                var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
                h = ((xf - mu) * jax.lax.rsqrt(
                    var + cfg.layer_norm_epsilon)
                    * lnf_scale.astype(jnp.float32)
                    + lnf_bias.astype(jnp.float32)).astype(cfg.dtype)
                wte_c = wte.astype(cfg.dtype)
                if cfg.loss_chunk > 0:
                    return chunked_lm_loss(h, wte_c, labels,
                                           cfg.loss_chunk)
                logits = jnp.einsum("bse,ve->bsv", h, wte_c)
                return lm_loss(logits, labels)

            def g(lnf_scale, lnf_bias, wte, x, labels):
                (loss, grads) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1, 2, 3))(
                        lnf_scale, lnf_bias, wte, x, labels)
                return loss, grads
            return jax.jit(g)
        return self._fn("head_grad", build)

    def _embed_grad(self):
        def build():
            def g(wte, wpe, ids, dx):
                fwd = lambda a, b: self._embed_fwd_math(a, b, ids)
                _, vjp = jax.vjp(fwd, wte, wpe)
                return vjp(dx)
            return jax.jit(g)
        return self._fn("embed_grad", build)

    def _embed_fwd_math(self, wte, wpe, ids):
        from deepspeed_tpu.models.gpt2 import _embed_lookup
        cfg = self.cfg
        return _embed_lookup(wte.astype(cfg.dtype), ids) \
            + wpe[:ids.shape[1]].astype(cfg.dtype)[None]

    def _row_update(self):
        """One jitted Adam over a layer row: donated pinned master/m/v in,
        updated pinned master/m/v out. Grad rows are sliced on-device from
        the segment grad stacks at a traced row index."""
        beta1, beta2 = self.betas
        eps, wd, adam_w = self.eps, self.wd, self.adam_w
        mdt = self._mdtype
        nleaf = len(self._blk_leaves)

        def build():
            def upd(masters, ms, vs, grads, row, lr, count):
                cf = count.astype(jnp.float32)
                bc1 = 1.0 - beta1 ** cf
                bc2 = 1.0 - beta2 ** cf
                out_w, out_m, out_v = [], [], []
                for i in range(nleaf):
                    p32 = jax.device_put(masters[i], self._dev_sh)
                    m32 = jax.device_put(ms[i], self._dev_sh) \
                        .astype(jnp.float32)
                    v32 = jax.device_put(vs[i], self._dev_sh)
                    g32 = jax.lax.dynamic_index_in_dim(
                        grads[i], row, axis=0, keepdims=False) \
                        .astype(jnp.float32)
                    if wd and not adam_w:
                        g32 = g32 + wd * p32
                    m_new = beta1 * m32 + (1.0 - beta1) * g32
                    v_new = beta2 * v32 + (1.0 - beta2) * (g32 * g32)
                    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
                    if wd and adam_w:
                        u = u + wd * p32
                    p_new = p32 - lr * u
                    out_w.append(jax.device_put(p_new, self._host_sh))
                    out_m.append(jax.device_put(m_new.astype(mdt),
                                                self._host_sh))
                    out_v.append(jax.device_put(v_new, self._host_sh))
                return tuple(out_w), tuple(out_m), tuple(out_v)
            return jax.jit(upd, donate_argnums=(0, 1, 2))
        return self._fn("row_update", build)

    def _emb_update(self):
        beta1, beta2 = self.betas
        eps, wd, adam_w = self.eps, self.wd, self.adam_w
        mdt = self._mdtype

        def build():
            def upd(masters, ms, vs, grads, lr, count):
                cf = count.astype(jnp.float32)
                bc1 = 1.0 - beta1 ** cf
                bc2 = 1.0 - beta2 ** cf
                out_w, out_m, out_v = [], [], []
                for p, m, v, g in zip(masters, ms, vs, grads):
                    p32 = jax.device_put(p, self._dev_sh)
                    m32 = jax.device_put(m, self._dev_sh) \
                        .astype(jnp.float32)
                    v32 = jax.device_put(v, self._dev_sh)
                    g32 = g.astype(jnp.float32)
                    if wd and not adam_w:
                        g32 = g32 + wd * p32
                    m_new = beta1 * m32 + (1.0 - beta1) * g32
                    v_new = beta2 * v32 + (1.0 - beta2) * (g32 * g32)
                    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
                    if wd and adam_w:
                        u = u + wd * p32
                    p_new = p32 - lr * u
                    out_w.append(jax.device_put(p_new, self._host_sh))
                    out_m.append(jax.device_put(m_new.astype(mdt),
                                                self._host_sh))
                    out_v.append(jax.device_put(v_new, self._host_sh))
                return tuple(out_w), tuple(out_m), tuple(out_v)
            return jax.jit(upd, donate_argnums=(0, 1, 2))
        return self._fn("emb_update", build)

    # --------------------------------------------------------------- step
    def train_batch(self, batch):
        """One full streamed step; returns the scalar loss (host float)."""
        cfg = self.cfg
        ids = jnp.asarray(batch["input_ids"])
        labels = jnp.asarray(batch.get("labels", batch["input_ids"]))
        self.step_count += 1
        lr = jnp.float32(self.lr_fn(self.step_count)
                         if self.lr_fn else self.lr)
        count = jnp.int32(self.step_count)

        # embeddings stay resident for the whole step (wte is shared by
        # embed and the tied head)
        emb_fetch = self._fn("emb_fetch", lambda: jax.jit(
            lambda *ls: tuple(
                jax.device_put(l, self._dev_sh).astype(cfg.param_dtype)
                for l in ls)))
        # flatten order of {"ln_f": {bias, scale}, "wpe", "wte"}
        lnf_bias, lnf_scale, wpe, wte = emb_fetch(*self.emb_master)

        # ---- forward: stream segments, keep boundaries
        x = self._embed_fwd()(wte, wpe, ids)
        bounds = [x]
        seg_fwd = self._seg_fwd()
        for k in range(self.K):
            ps = self._fetch_seg(k)
            x = seg_fwd(tuple(ps), x)
            bounds.append(x)
            for p in ps:
                p.delete()

        # ---- head loss + its grads
        loss, (d_lnf_s, d_lnf_b, d_wte_head, dx) = self._head_grad()(
            lnf_scale, lnf_bias, wte, bounds[-1], labels)

        # ---- backward: re-fetch each segment, vjp, stream the row updates
        seg_grad = self._seg_grad()
        row_update = self._row_update()
        for k in reversed(range(self.K)):
            ps = self._fetch_seg(k)
            dps, dx = seg_grad(tuple(ps), bounds[k], dx)
            for p in ps:
                p.delete()
            for rloc in range(self.rows):
                r = k * self.rows + rloc
                w, m, v = row_update(
                    tuple(self.master[r]), tuple(self.m[r]),
                    tuple(self.v[r]), dps, jnp.int32(rloc), lr, count)
                self.master[r] = list(w)
                self.m[r], self.v[r] = list(m), list(v)
            for g in dps:
                g.delete()
            bounds[k + 1].delete()

        # ---- embedding grads + update
        d_wte_emb, d_wpe = self._embed_grad()(wte, wpe, ids, dx)
        add = self._fn("addcast", lambda: jax.jit(
            lambda a, b: a.astype(jnp.float32) + b.astype(jnp.float32)))
        d_wte = add(d_wte_head, d_wte_emb)
        grads = jax.tree_util.tree_leaves(
            {"wte": d_wte, "wpe": d_wpe,
             "ln_f": {"scale": d_lnf_s, "bias": d_lnf_b}})
        w, m, v = self._emb_update()(
            tuple(self.emb_master), tuple(self.emb_m), tuple(self.emb_v),
            tuple(grads), lr, count)
        self.emb_master, self.emb_m, self.emb_v = list(w), list(m), list(v)

        if self._swapper and self.param_bytes <= self._park_threshold:
            self.park_to_nvme()
        return float(jax.device_get(loss))

    # ------------------------------------------------------ NVMe residency
    def park_to_nvme(self):
        """Refresh the at-rest NVMe param files from the pinned masters
        (d2h + write — at multi-GB scale this is checkpoint-cadence work
        on disaggregated deployments; see module docstring)."""
        assert self._swapper is not None
        pdt = self._np_pdtype()
        leaves = [np.asarray(l).astype(pdt) for l in self.emb_master]
        for i in range(len(self._blk_leaves)):
            stack = np.stack([np.asarray(self.master[r][i]).astype(pdt)
                              for r in range(self.cfg.n_layer)])
            leaves.append(stack)
        self._swapper.write_all(leaves)

    def restore_from_nvme(self):
        """Cold start: rebuild the pinned fp32 masters from the NVMe
        param files (the at-scale disk-read path; moments reset)."""
        assert self._swapper is not None
        n_emb = len(self._emb_leaves)
        metas = self._swapper.meta
        place_row = self._fns.get("place_row") or jax.jit(
            lambda *ls: tuple(
                jax.device_put(jnp.asarray(l).astype(jnp.float32),
                               self._host_sh) for l in ls))
        self._fns["place_row"] = place_row
        bufs = []
        for i in range(len(metas)):
            shape, dtype = metas[i]
            arr = np.empty(int(np.prod(shape)) * dtype.itemsize, np.uint8)
            self._swapper.handle.sync_pread(arr, self._swapper._path(i))
            bufs.append(arr.view(dtype).reshape(shape))
        self.emb_master = list(place_row(*bufs[:n_emb]))
        blk = bufs[n_emb:]
        for r in range(self.cfg.n_layer):
            self.master[r] = list(place_row(*[b[r] for b in blk]))

    def params_on_disk_bytes(self):
        if not self._swapper:
            return 0
        return sum(os.path.getsize(self._swapper._path(i))
                   for i in range(len(self._swapper.meta)))

    def release(self):
        """Reclaim the durable NVMe files (they intentionally survive
        the process otherwise — see the at-rest contract in __init__)."""
        if self._swapper is not None:
            self._swapper.release()

    # ------------------------------------------------------- engine parity
    @classmethod
    def from_config(cls, model, ds_config, model_parameters=None,
                    device=None):
        """Build from a parsed DeepSpeedConfig (the ``initialize()``
        dispatch for ``offload_param.stream_segments > 0``). Large models
        should pass ``model_parameters=None`` and let the client-side
        numpy init build the tree without materializing the model."""
        cfg = model.config
        params = model_parameters if model_parameters is not None \
            else gpt2_client_init(cfg, seed=ds_config.seed)
        op = dict(ds_config.optimizer_params or {})
        adam_w = str(ds_config.optimizer_name or "adamw").lower() == "adamw"
        return cls(
            cfg, params, device=device,
            segments=ds_config.zero_config.offload_param.stream_segments,
            nvme_path=ds_config.zero_config.offload_param.nvme_path,
            lr=float(op.get("lr", 1e-4)),
            betas=tuple(op.get("betas", (0.9, 0.999))),
            eps=float(op.get("eps", 1e-8)),
            weight_decay=float(op.get("weight_decay", 0.0)),
            adam_w=adam_w)

    # the initialize() return-tuple surface
    optimizer = None
    training_dataloader = None
    lr_scheduler = None

    # ------------------------------------------------------------ export
    def params_tree(self, dtype=np.float32):
        """Full parameter pytree on the CLIENT host (d2h — checkpoint
        cadence at scale)."""
        blk_full = []
        for i, shape in enumerate(self._blk_shapes):
            blk_full.append(np.stack(
                [np.asarray(self.master[r][i]).astype(dtype)
                 for r in range(self.cfg.n_layer)]))
        tree = {"h": {"blk": jax.tree_util.tree_unflatten(
            self._blk_def, blk_full)}}
        emb = jax.tree_util.tree_unflatten(
            self._emb_def, [np.asarray(l).astype(dtype)
                            for l in self.emb_master])
        tree.update(emb)
        return tree
