"""Defragmenting tensor arena — rebuild of the reference's
ContiguousMemoryAllocator (zero/contiguous_memory_allocator.py:9).

One contiguous host buffer serves many tensor-sized sub-allocations; when
free space is sufficient but fragmented, ``allocate`` compacts live tensors
to the front of the buffer (preserving contents) and retries — the
reference's memory-defragmentation move (:112-160). On TPU this arena backs
host-side staging: pinned swap buffers for the NVMe optimizer/param tiers
and contiguous activation staging, where allocation churn and fragmentation
otherwise fight the aio path's alignment requirements.

Tensors are numpy views into the arena; a move during defragmentation
preserves values but REPLACES the view object — callers access live
tensors through ``get_tensor(tensor_id)`` after any allocate() (the
reference instead re-points module params, :14-18 comment block).
"""

import numpy as np

from deepspeed_tpu.utils.logging import logger


class ContiguousMemoryAllocator:
    def __init__(self, size, dtype=np.float32, align_elems=1):
        """``align_elems`` > 1 makes every sub-allocation start on a
        multiple of that many ELEMENTS from a page-aligned base (sizes
        round up internally) — the O_DIRECT swap tier (ISSUE 20) stages
        through such an arena so its slices submit zero-copy through the
        aio alignment layer. Default 1 keeps the historical layout."""
        self.dtype = np.dtype(dtype)
        self.align_elems = max(1, int(align_elems))
        size = -(-int(size) // self.align_elems) * self.align_elems
        if self.align_elems > 1:
            from deepspeed_tpu.ops.native.aio import aligned_empty
            self.buffer = aligned_empty(size * self.dtype.itemsize) \
                .view(self.dtype)
            self.buffer[:] = 0
        else:
            self.buffer = np.zeros(size, dtype)
        self.size = size

        # offset → length of free block (reference self.contiguous_sizes)
        self.free_blocks = {0: self.size}
        # tensor_id → (offset, alloc numel); views live in self.tensor_map
        self.tensor_addresses = {}
        self.tensor_sizes = {}     # ROUNDED allocation size (carve/free)
        self.tensor_numels = {}    # requested size (view length)
        self.tensor_map = {}

        self.total_free = self.size
        self.max_allocated = 0
        self.count = 0

    # -- public API (reference :25-110) ---------------------------------
    def allocate_tensor(self, numel):
        """Returns (tensor_id, view). Asserts there is enough total free
        space; defragments when no single free block fits."""
        numel = int(numel)
        alloc = -(-numel // self.align_elems) * self.align_elems
        assert alloc <= self.total_free, (
            f"arena exhausted: need {alloc}, free {self.total_free}")
        if self._largest_free() < alloc:
            logger.info(
                f"arena defragment: need {alloc} contiguous, largest free "
                f"{self._largest_free()} of {self.total_free} total")
            self._defragment()
        offset = self._find_block(alloc)
        assert offset is not None
        self._carve(offset, alloc)
        self.count += 1
        tid = self.count
        view = self.buffer[offset:offset + numel]
        self.tensor_addresses[tid] = offset
        self.tensor_sizes[tid] = alloc
        self.tensor_numels[tid] = numel
        self.tensor_map[tid] = view
        self.total_free -= alloc
        self.max_allocated = max(self.max_allocated,
                                 self.size - self.total_free)
        return tid, view

    def get_tensor(self, tensor_id):
        """Current live view (revalidate after any allocate/defragment)."""
        return self.tensor_map[tensor_id]

    def release_tensor(self, tensor_id):
        offset = self.tensor_addresses.pop(tensor_id)
        numel = self.tensor_sizes.pop(tensor_id)
        self.tensor_numels.pop(tensor_id, None)
        del self.tensor_map[tensor_id]
        self.total_free += numel
        self._free(offset, numel)

    def allocated_ids(self):
        return sorted(self.tensor_addresses)

    def print_allocation(self):
        logger.info(
            f"arena: size={self.size} free={self.total_free} "
            f"live={len(self.tensor_addresses)} "
            f"largest_free={self._largest_free()}")

    # -- internals -------------------------------------------------------
    def _largest_free(self):
        return max(self.free_blocks.values(), default=0)

    def _find_block(self, numel):
        best = None
        for off, length in self.free_blocks.items():
            if length >= numel and (best is None or length < best[1]):
                best = (off, length)
        return best[0] if best else None

    def _carve(self, offset, numel):
        length = self.free_blocks.pop(offset)
        if length > numel:
            self.free_blocks[offset + numel] = length - numel

    def _free(self, offset, numel):
        # merge with adjacent free blocks (reference :162-199)
        end = offset + numel
        nxt = self.free_blocks.pop(end, None)
        if nxt is not None:
            numel += nxt
        for off in list(self.free_blocks):
            if off + self.free_blocks[off] == offset:
                offset = off
                numel += self.free_blocks.pop(off)
                break
        self.free_blocks[offset] = numel

    def _defragment(self):
        """Compact live tensors to the front in address order, copying
        contents and re-pointing views (reference :112-160)."""
        cursor = 0
        for tid in sorted(self.tensor_addresses,
                          key=lambda t: self.tensor_addresses[t]):
            offset = self.tensor_addresses[tid]
            numel = self.tensor_numels.get(tid, self.tensor_sizes[tid])
            if offset != cursor:
                # regions may overlap when sliding left; numpy handles
                # overlapping same-buffer copies for a leftward move via
                # an explicit copy of the source
                self.buffer[cursor:cursor + numel] = \
                    self.buffer[offset:offset + numel].copy()
                self.tensor_addresses[tid] = cursor
                self.tensor_map[tid] = self.buffer[cursor:cursor + numel]
            cursor += self.tensor_sizes[tid]
        self.free_blocks = {cursor: self.size - cursor} \
            if cursor < self.size else {}
