"""ZeRO stages 1/2/3 as GSPMD sharding rules.

The reference implements ZeRO as ~6,300 lines of imperative partition
bookkeeping (zero/stage1.py:57, stage2.py:68, stage3.py:581,
partition_parameters.py:450-545). On TPU the same *memory states* are
expressed declaratively and XLA inserts the collectives:

  stage 0: params, grads, optimizer state all replicated over the data axis
           (plain DP — grad psum).
  stage 1: optimizer state sharded over the data axis; grads replicated
           (all-reduce), each shard of the update computed locally, updated
           params all-gathered — exactly the reference's sub-partition
           scheme (stage1.py:305) with XLA choosing the bucketing.
  stage 2: + gradients sharded: the grad sharding constraint turns the
           backward all-reduce into reduce-scatter (+ all-gather of updated
           params) — the reference's IPG-bucket reduce-scatter
           (stage2.py:614-746).
  stage 3: + parameters sharded at rest. Forward/backward all-gathers each
           layer's params just-in-time; with scanned layers XLA overlaps the
           gather of layer i+1 with compute of layer i — the reference's
           PartitionedParameterCoordinator prefetch (stage3.py:287-447)
           falls out of the schedule.

Sharding choice per tensor: the largest dimension not already occupied by a
tensor-parallel axis, provided it divides by the data-axis size; otherwise
the tensor stays replicated (the analog of the reference's
`param_persistence_threshold` — small tensors aren't worth partitioning,
stage3.py constants ZERO_PARAM_PERSISTENCE_THRESHOLD).
"""

from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel import mesh as mesh_lib


def shard_spec_for_leaf(shape,
                        dp_size: int,
                        base_spec: Optional[PartitionSpec] = None,
                        min_size: int = 0,
                        axis_name: str = mesh_lib.DATA_AXIS,
                        exclude_dims=()) -> PartitionSpec:
    """Extend ``base_spec`` (TP sharding) with a data-axis shard on the
    largest free, divisible dimension. Returns base_spec unchanged if no
    dimension qualifies or the tensor is below ``min_size`` elements.
    ``exclude_dims`` removes dimensions from candidacy — the prefetch
    pipeline needs layer-stacked leaves whole along their layer dim."""
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (len(shape) - len(base))
    if dp_size <= 1 or int(np.prod(shape or (1,))) < max(min_size, dp_size):
        return PartitionSpec(*base)
    # candidate dims: unsharded, divisible by dp, largest first
    candidates = sorted(
        (d for d in range(len(shape))
         if d not in exclude_dims and base[d] is None
         and shape[d] % dp_size == 0 and shape[d] >= dp_size),
        key=lambda d: shape[d], reverse=True)
    if not candidates:
        return PartitionSpec(*base)
    d = candidates[0]
    new = list(base)
    new[d] = axis_name
    return PartitionSpec(*new)


class ZeroPartitioner:
    """Produces NamedShardings for params / grads / optimizer state given the
    configured ZeRO stage. ``tp_specs`` is an optional pytree of
    PartitionSpec matching the params tree carrying tensor-parallel axes."""

    def __init__(self, mesh: Mesh, stage: int, tp_specs=None,
                 param_persistence_threshold: int = 0,
                 param_memory_kind=None):
        assert 0 <= stage <= 3
        self.mesh = mesh
        self.stage = stage
        self.tp_specs = tp_specs
        self.dp = mesh_lib.mesh_axis_size(mesh, mesh_lib.DATA_AXIS)
        self.min_size = int(param_persistence_threshold)
        # "pinned_host" = ZeRO-Offload/Infinity param tier: params rest in
        # host DRAM (reference offload_param, partitioned_param_swapper.py:36)
        # and stream to HBM inside the step via device_put
        self.param_memory_kind = param_memory_kind
        # top-level param-tree keys whose leaves are layer-stacked
        # ([L, ...]): their dim 0 is never a shard candidate, so the
        # stage3_prefetch pipeline can slice whole layers device-locally
        # (the engine sets this when the prefetch path is active)
        self.layer_stacked_prefixes = ()

    # -- spec trees --------------------------------------------------------
    def _base_spec(self, path, leaf):
        if self.tp_specs is None:
            return None
        # tp_specs is a matching tree; fetch by path
        sub = self.tp_specs
        try:
            for p in path:
                key = getattr(p, "key", None)
                if key is None:
                    key = getattr(p, "idx", None)
                if key is None:
                    key = getattr(p, "name", None)
                sub = sub[key]
            return sub
        except (KeyError, TypeError, IndexError):
            return None

    def _zero_spec(self, path, leaf):
        base = self._base_spec(path, leaf)
        exclude = ()
        if self.layer_stacked_prefixes and path:
            head = getattr(path[0], "key", getattr(path[0], "name", None))
            if head in self.layer_stacked_prefixes:
                exclude = (0,)
        return shard_spec_for_leaf(leaf.shape, self.dp, base,
                                   min_size=self.min_size,
                                   exclude_dims=exclude)

    def _tp_only_spec(self, path, leaf):
        base = self._base_spec(path, leaf)
        base = tuple(base) if base is not None else ()
        base = base + (None,) * (len(leaf.shape) - len(base))
        return PartitionSpec(*base)

    def param_specs(self, params):
        """Stage 3 shards params at rest; stages 0-2 keep them replicated
        (modulo TP axes)."""
        fn = self._zero_spec if self.stage >= 3 else self._tp_only_spec
        return jax.tree_util.tree_map_with_path(fn, params)

    def grad_specs(self, params):
        """Stage >=2: sharded grads (reduce-scatter); else same as params."""
        fn = self._zero_spec if self.stage >= 2 else self._tp_only_spec
        return jax.tree_util.tree_map_with_path(fn, params)

    def opt_param_like_specs(self, params):
        """Stage >=1: shard optimizer moments like stage-3 params."""
        fn = self._zero_spec if self.stage >= 1 else self._tp_only_spec
        return jax.tree_util.tree_map_with_path(fn, params)

    # -- sharding trees ----------------------------------------------------
    def _named(self, spec_tree):
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self, params):
        """Resting shardings (host-memory-kind when the param offload tier
        is on)."""
        sh = self._named(self.param_specs(params))
        if self.param_memory_kind:
            sh = jax.tree_util.tree_map(
                lambda s: s.with_memory_kind(self.param_memory_kind), sh)
        return sh

    def device_param_shardings(self, params):
        """Compute-time shardings: always default (HBM) memory."""
        return self._named(self.param_specs(params))

    def grad_shardings(self, params):
        return self._named(self.grad_specs(params))

    def opt_state_shardings(self, opt_state, params, param_like_fields):
        """Build shardings for the optimizer-state dict: fields listed in
        ``param_like_fields`` mirror the param tree and get ZeRO specs;
        everything else (step counters, scalars) is replicated."""
        moment_shardings = self._named(self.opt_param_like_specs(params))
        out = {}
        for key, sub in opt_state.items():
            if key in param_like_fields:
                out[key] = moment_shardings
            else:
                out[key] = jax.tree_util.tree_map(
                    lambda _: NamedSharding(self.mesh, PartitionSpec()), sub)
        return out

    def explicit_shard_plan(self, params, specs=None):
        """Per-leaf update ownership for the explicit-comm (shard_map)
        overlap train path: a list aligned with ``tree_leaves(params)`` of
        ``(dim, shard_size)`` — the data-axis dim the stage>=1 optimizer
        state shards over and the per-device extent — or ``None`` for
        leaves whose moments stay replicated (every device runs their full
        update redundantly, which is exact). Inside shard_map the owner
        device updates params[dim slice] with its local moment shard and
        the slices all-gather back (the stage-1/2 updated-param all-gather,
        stage2.py:~1470, made explicit). ``specs`` overrides the moment
        spec tree (the stage3_prefetch path passes its param specs so
        the plan matches the resting layout exactly)."""
        from deepspeed_tpu.parallel.prefetch import plan_from_specs
        leaves = jax.tree_util.tree_leaves(params)
        if specs is None:
            specs = self.opt_param_like_specs(params)
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec))
        return plan_from_specs(leaves, spec_leaves, mesh_lib.DATA_AXIS,
                               self.dp)

    def constrain_grads(self, grads):
        """Apply the stage>=2 reduce-scatter constraint inside the train step."""
        if self.stage < 2:
            return grads
        specs = self.grad_specs(grads)
        return jax.tree_util.tree_map(
            lambda g, s: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.mesh, s)),
            grads, specs)
