"""TPU-native ZeRO-Offload: optimizer state in device-host DRAM, update
streamed on device.

The reference's ZeRO-Offload (stage2.py:747-925 + csrc/adam/cpu_adam.cpp:21)
moves gradients over PCIe to the host, runs a SIMD Adam on host cores, and
copies updated params back — the right architecture when the accelerator
host has fat cores and the grads already cross PCIe for the NCCL reduction.
On TPU neither holds: XLA exposes the host DRAM *as a device memory space*
(``memory_kind="pinned_host"``), so the TPU-native realization of the same
memory shape — fp32 master + Adam moments in host DRAM, zero HBM resident
optimizer state — keeps the *step on the device* and streams the state
through HBM in bounded chunks:

    master/m/v (pinned_host) --DMA--> HBM chunk --VPU update--> back to
    pinned_host; bf16 params out to HBM for the next forward.

One step therefore moves 2x the state bytes over the device's host link
(PCIe-class, ~9-10 GB/s measured) instead of moving gradients + params over
whatever link connects the *client* process to the chip — on tunneled or
disaggregated deployments that link is orders of magnitude slower, and on
a TPU-VM this path still wins: the VPU applies the update at HBM bandwidth
and no host SIMD library or core count is on the critical path.

HBM discipline (the analog of the reference's tiled pinned-buffer bounds,
swap_tensor/optimizer_utils.py): state is stored pre-chunked — leaves whose
fp32 bytes exceed ``unit_bytes`` are split along their leading (layer) dim
into separate pinned_host arrays — and chunks are packed into per-program
groups of ≤ ``unit_bytes`` fp32 state, so one program's HBM staging is one
group's worth. Gradient leaves stay whole in HBM; each program slices its
units' windows on-device and the LAST program touching a leaf takes it
donated, so gradient HBM frees progressively as updated params accumulate.

Used by the engine when ``offload_optimizer.device == "cpu"`` and the
backend exposes a pinned_host memory space; the numpy/SIMD
`HostOffloadOptimizer` (offload.py) remains the NVMe tier and the explicit
``stream: "host"`` fallback.
"""

import dataclasses
from typing import List

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.utils.logging import logger


def host_memory_kind(device=None):
    """The backend's host-side memory kind for resting optimizer state:
    ``pinned_host`` where the platform has a distinct DMA-able host space
    (TPU), else the backend's default kind (the XLA CPU backend collapses
    memory spaces — host IS device memory, exposed only as
    ``unpinned_host`` — so the streamed tier runs there with no-op moves
    and identical semantics). None when the backend reports nothing."""
    try:
        dev = device or jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:
        return None
    if "pinned_host" in kinds:
        return "pinned_host"
    return _default_memory_kind(device) or next(iter(sorted(kinds)), None)


def _default_memory_kind(device=None):
    try:
        dev = device or jax.devices()[0]
        return dev.default_memory().kind
    except Exception:
        return None


def backend_supports_offload_stream(device=None) -> bool:
    """True when the streamed tier can place its state somewhere the
    backend names — every current backend; kept as a guard for exotic
    PJRT plugins that report no memories at all."""
    return host_memory_kind(device) is not None


@dataclasses.dataclass(frozen=True)
class _Unit:
    """One streamed window: rows [start, stop) of leaf ``leaf`` (the whole
    leaf when the leaf is small or has no splittable leading dim)."""
    leaf: int
    start: int
    stop: int          # 0/0 for unsplit leaves

    @property
    def split(self):
        return self.stop > 0


class StreamedOffloadOptimizer:
    """Adam/AdamW with fp32 master + moments resident in pinned_host.

    Interface mirrors HostOffloadOptimizer where the engine touches it
    (``step_count``, ``params_tree``, ``state_dict``, ``load_state_dict``);
    the step itself is ``step(grad_leaves, lr, grad_scale, out_dtype)`` →
    updated compute-dtype param leaves resting in device memory.
    """

    def __init__(self, params, optimizer, mesh, partitioner,
                 unit_bytes: int = 512 * 1024 * 1024):
        from deepspeed_tpu.ops.adam import FusedAdam
        from deepspeed_tpu.ops.lamb import FusedLamb
        if isinstance(optimizer, FusedLamb) or \
                not isinstance(optimizer, FusedAdam):
            raise ValueError(
                "streamed offload supports Adam/AdamW (per-element update); "
                f"got {type(optimizer).__name__} — the host runner handles "
                "LAMB (whole-leaf trust ratios)")
        self.optimizer = optimizer
        self.mesh = mesh
        self.zero = partitioner
        self.step_count = 0
        dev0 = mesh.devices.flat[0]
        self.host_memory_kind = host_memory_kind(dev0)
        self.device_memory_kind = _default_memory_kind(dev0) or "device"
        if self.host_memory_kind is None:
            raise ValueError(
                "streamed offload: backend reports no addressable "
                "memories; use the host runner (stream='host')")
        self._mdtype = jnp.bfloat16 \
            if getattr(optimizer, "moment_dtype", "fp32") == "bf16" \
            else jnp.float32

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [tuple(l.shape) for l in leaves]
        n = len(leaves)

        # per-leaf specs: opt state lives in the ZeRO opt sharding; params
        # rest in the param sharding. Memory-kind moves keep the spec fixed
        # (host<->HBM is a pure DMA); spec moves happen in device space.
        opt_spec_tree = partitioner.opt_param_like_specs(params)
        self.opt_specs = jax.tree_util.tree_leaves(
            opt_spec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))
        param_spec_tree = partitioner.param_specs(params)
        self.param_specs = jax.tree_util.tree_leaves(
            param_spec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))
        assert len(self.opt_specs) == n and len(self.param_specs) == n
        self.param_memory_kind = partitioner.param_memory_kind \
            or self.device_memory_kind

        # split big leaves along dim0 into units of <= unit_bytes fp32
        self.units: List[_Unit] = []
        for i, shape in enumerate(self.shapes):
            nbytes = int(np.prod(shape or (1,))) * 4
            d0 = shape[0] if shape else 1
            if nbytes <= unit_bytes or d0 <= 1 or \
                    self._spec_shards_dim0(self.opt_specs[i]):
                if nbytes > 2 * unit_bytes:
                    logger.warning(
                        f"streamed offload: leaf {i} {shape} "
                        f"({nbytes >> 20} MiB fp32) cannot be split along "
                        f"dim0; it streams as one window")
                self.units.append(_Unit(i, 0, 0))
                continue
            k = -(-nbytes // unit_bytes)          # ceil
            rows = -(-d0 // k)
            for s in range(0, d0, rows):
                self.units.append(_Unit(i, s, min(s + rows, d0)))

        # pack units into per-program groups of <= unit_bytes fp32 state
        self.groups: List[List[_Unit]] = []
        cur, cur_b = [], 0
        for u in self.units:
            b = self._unit_elems(u) * 4
            if cur and cur_b + b > unit_bytes:
                self.groups.append(cur)
                cur, cur_b = [], 0
            cur.append(u)
            cur_b += b
        if cur:
            self.groups.append(cur)
        # the last group touching each leaf takes its gradient donated
        self._last_group_of_leaf = {}
        for gi, g in enumerate(self.groups):
            for u in g:
                self._last_group_of_leaf[u.leaf] = gi

        # state storage: per-unit pinned_host arrays
        self.master: List = [None] * len(self.units)
        self.m: List = [None] * len(self.units)
        self.v: List = [None] * len(self.units)
        for gi, group in enumerate(self.groups):
            place = jax.jit(
                lambda *ls, us=tuple(group): tuple(
                    jax.device_put(l.astype(jnp.float32), self._host_sh(u))
                    for l, u in zip(ls, us)))
            placed = place(*[self._slice_leaf(leaves[u.leaf], u)
                             for u in group])
            zeros = jax.jit(
                lambda us=tuple(group): tuple(
                    (jax.device_put(
                        jnp.zeros(self._unit_shape(u), self._mdtype),
                        self._host_sh(u)),
                     jax.device_put(
                        jnp.zeros(self._unit_shape(u), jnp.float32),
                        self._host_sh(u))) for u in us))
            for u, arr, (zm, zv) in zip(group, placed, zeros()):
                ui = self.units.index(u)
                self.master[ui] = arr
                self.m[ui], self.v[ui] = zm, zv
        self._unit_index = {u: i for i, u in enumerate(self.units)}
        self._group_fns = {}
        logger.info(
            f"StreamedOffloadOptimizer: {n} leaves -> {len(self.units)} "
            f"stream units in {len(self.groups)} programs; moments "
            f"{'bf16' if self._mdtype == jnp.bfloat16 else 'fp32'} + fp32 "
            f"master resident in {self.host_memory_kind}")

    # -- unit geometry -----------------------------------------------------
    @staticmethod
    def _spec_shards_dim0(spec):
        entries = tuple(spec)
        return bool(entries) and entries[0] is not None

    def _unit_shape(self, u: _Unit):
        shape = self.shapes[u.leaf]
        if not u.split:
            return shape
        return (u.stop - u.start,) + shape[1:]

    def _unit_elems(self, u: _Unit):
        return int(np.prod(self._unit_shape(u) or (1,)))

    @staticmethod
    def _slice_leaf(leaf, u: _Unit):
        if not u.split:
            return leaf
        return jax.lax.slice_in_dim(leaf, u.start, u.stop, axis=0)

    def _host_sh(self, u: _Unit):
        return NamedSharding(self.mesh, self.opt_specs[u.leaf],
                             memory_kind=self.host_memory_kind)

    def _stage_sh(self, u: _Unit):
        return NamedSharding(self.mesh, self.opt_specs[u.leaf],
                             memory_kind=self.device_memory_kind)

    # -- the step ----------------------------------------------------------
    def _build_group_fn(self, gi, out_dtype):
        """One jitted program per group: device_put each unit's host state
        into HBM, apply Adam on the unit's on-device gradient window, write
        state back to pinned_host and emit the compute-dtype param chunk.
        Host state args are donated (in-place update semantics); gradient
        leaves are donated only in their last group."""
        opt = self.optimizer
        beta1, beta2 = opt.betas
        eps, wd = opt.eps, opt.weight_decay
        adam_w, bias_c = opt.adam_w_mode, opt.bias_correction
        group = self.groups[gi]
        g_leaves = sorted({u.leaf for u in group})
        g_pos = {l: k for k, l in enumerate(g_leaves)}
        donate_leaves = tuple(
            k + 3 for k, l in enumerate(g_leaves)
            if self._last_group_of_leaf[l] == gi)
        mdtype = self._mdtype

        def group_step(masters, ms, vs, *rest):
            grads = rest[:len(g_leaves)]
            lr, coef, count = rest[len(g_leaves):]
            cf = count.astype(jnp.float32)
            bc1 = 1.0 - beta1 ** cf if bias_c else jnp.float32(1.0)
            bc2 = 1.0 - beta2 ** cf if bias_c else jnp.float32(1.0)
            outs_p, outs_w, outs_m, outs_v = [], [], [], []
            for master, m, v, u in zip(masters, ms, vs, group):
                ss = self._stage_sh(u)
                p32 = jax.device_put(master, ss)
                m32 = jax.device_put(m, ss).astype(jnp.float32)
                v32 = jax.device_put(v, ss)
                g = self._slice_leaf(grads[g_pos[u.leaf]], u)
                g32 = jax.lax.with_sharding_constraint(
                    g.astype(jnp.float32), ss) * coef
                if wd != 0.0 and not adam_w:
                    g32 = g32 + wd * p32
                m_new = beta1 * m32 + (1.0 - beta1) * g32
                v_new = beta2 * v32 + (1.0 - beta2) * (g32 * g32)
                upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
                if wd != 0.0 and adam_w:
                    upd = upd + wd * p32
                p_new = p32 - lr * upd
                outs_p.append(p_new.astype(out_dtype))
                outs_w.append(jax.device_put(p_new, self._host_sh(u)))
                outs_m.append(jax.device_put(m_new.astype(mdtype),
                                             self._host_sh(u)))
                outs_v.append(jax.device_put(v_new, self._host_sh(u)))
            return (tuple(outs_p), tuple(outs_w),
                    tuple(outs_m), tuple(outs_v))

        return jax.jit(group_step,
                       donate_argnums=(0, 1, 2) + donate_leaves)

    def _assemble_leaf(self, leaf_idx, chunks, out_dtype):
        """Reassemble a leaf's param from its unit chunks and move it to
        the resting param sharding (spec move in device space, memory-kind
        move as a same-spec DMA when the pinned-host param tier is on)."""
        dev_sh = NamedSharding(self.mesh, self.param_specs[leaf_idx],
                               memory_kind=self.device_memory_kind)
        key = (leaf_idx, jnp.dtype(out_dtype).name, len(chunks))
        fn = self._group_fns.get(("asm", key))
        if fn is None:
            def assemble(*cs):
                x = cs[0] if len(cs) == 1 else jnp.concatenate(cs, axis=0)
                x = jax.lax.with_sharding_constraint(x, dev_sh)
                if self.param_memory_kind != self.device_memory_kind:
                    x = jax.device_put(x, NamedSharding(
                        self.mesh, self.param_specs[leaf_idx],
                        memory_kind=self.param_memory_kind))
                return x
            fn = self._group_fns[("asm", key)] = jax.jit(
                assemble, donate_argnums=tuple(range(len(chunks))))
        return fn(*chunks)

    def step(self, grad_leaves, lr: float, grad_scale: float = 1.0,
             out_dtype=jnp.bfloat16):
        """Stream-update every group; returns new param leaves (device,
        ``out_dtype``). Programs dispatch back-to-back — JAX dispatch is
        async, so one group's host reads overlap the previous group's tail
        writes on the full-duplex host link."""
        self.step_count += 1
        n = len(self.shapes)
        assert len(grad_leaves) == n, (len(grad_leaves), n)
        lr = jnp.float32(lr)
        coef = jnp.float32(grad_scale)
        count = jnp.int32(self.step_count)
        chunks = [[] for _ in range(n)]
        new_params: List = [None] * n
        for gi, group in enumerate(self.groups):
            key = (gi, jnp.dtype(out_dtype).name)
            fn = self._group_fns.get(key)
            if fn is None:
                fn = self._group_fns[key] = self._build_group_fn(
                    gi, out_dtype)
            g_leaves = sorted({u.leaf for u in group})
            uis = [self._unit_index[u] for u in group]
            ps, ws, ms, vs = fn(
                tuple(self.master[ui] for ui in uis),
                tuple(self.m[ui] for ui in uis),
                tuple(self.v[ui] for ui in uis),
                *[grad_leaves[l] for l in g_leaves],
                lr, coef, count)
            for j, (u, ui) in enumerate(zip(group, uis)):
                chunks[u.leaf].append(ps[j])
                self.master[ui] = ws[j]
                self.m[ui] = ms[j]
                self.v[ui] = vs[j]
            for l in g_leaves:
                if self._last_group_of_leaf[l] == gi:
                    new_params[l] = self._assemble_leaf(
                        l, chunks[l], out_dtype)
                    chunks[l] = None
        return new_params

    # -- checkpoint interface (HostOffloadOptimizer parity) ----------------
    def _gather_leaf(self, store, leaf_idx, dtype):
        parts = [np.asarray(store[self._unit_index[u]])
                 for u in self.units if u.leaf == leaf_idx]
        full = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)
        return np.asarray(full, dtype)

    def params_tree(self):
        return jax.tree_util.tree_unflatten(
            self.treedef,
            [self._gather_leaf(self.master, i, np.float32)
             for i in range(len(self.shapes))])

    def state_dict(self):
        n = len(self.shapes)
        return {
            "step": self.step_count,
            "exp_avg": jax.tree_util.tree_unflatten(
                self.treedef,
                [self._gather_leaf(self.m, i, np.float32) for i in range(n)]),
            "exp_avg_sq": jax.tree_util.tree_unflatten(
                self.treedef,
                [self._gather_leaf(self.v, i, np.float32) for i in range(n)]),
        }

    def load_state_dict(self, sd):
        self.step_count = int(np.asarray(sd["step"]))
        m = jax.tree_util.tree_leaves(sd["exp_avg"])
        v = jax.tree_util.tree_leaves(sd["exp_avg_sq"])
        for ui, u in enumerate(self.units):
            # place through a jit: eager device_put from numpy ALIASES the
            # numpy buffer on the CPU backend, and the step's donation of
            # an externally-owned buffer aborts the runtime
            place = jax.jit(
                lambda a, b, u=u: (
                    jax.device_put(a.astype(self._mdtype), self._host_sh(u)),
                    jax.device_put(b.astype(jnp.float32), self._host_sh(u))))
            mw = self._slice_np(np.asarray(m[u.leaf]), u)
            vw = self._slice_np(np.asarray(v[u.leaf]), u)
            self.m[ui], self.v[ui] = place(mw, vw)

    @staticmethod
    def _slice_np(arr, u: _Unit):
        return arr if not u.split else arr[u.start:u.stop]
