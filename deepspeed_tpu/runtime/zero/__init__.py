from deepspeed_tpu.runtime.zero.partition import (
    ZeroPartitioner,
    shard_spec_for_leaf,
)
from deepspeed_tpu.runtime.zero.init import Init, GatheredParameters, sharded_init
from deepspeed_tpu.runtime.zero.tiling import TiledLinear, TiledLinearReturnBias
from deepspeed_tpu.runtime.zero.linear import ZeroLinear, memory_efficient_dot
