from deepspeed_tpu.runtime.zero.partition import (
    ZeroPartitioner,
    shard_spec_for_leaf,
)
