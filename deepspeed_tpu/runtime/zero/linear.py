"""Memory-efficient linear for ZeRO-3 — rebuild of
deepspeed/runtime/zero/linear.py:38 (LinearFunctionForZeroStage3).

The reference writes a custom autograd Function so the *gathered* weight is
not saved for backward (only the partitioned shard survives; backward
re-gathers). In JAX the identical effect is a remat policy: checkpoint the
dot but don't save the gathered operand — XLA re-materializes the
all-gather in the backward pass. `memory_efficient_dot` wraps any matmul in
that policy; `ZeroLinear` is the drop-in Dense.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp
import flax.linen as nn

# Save only activations that are NOT produced by an all-gather of sharded
# params: offloadable-dots policy keeps matmul outputs, recomputes gathers.
_policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims


def memory_efficient_dot(x, w):
    """y = x @ w without keeping the gathered w for backward."""

    @jax.checkpoint
    def _dot(x_, w_):
        return jnp.matmul(x_, w_)

    return _dot(x, w)


class ZeroLinear(nn.Module):
    """Dense layer whose backward re-gathers the weight instead of saving it
    (pairs with ZeRO-3 param sharding)."""
    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (x.shape[-1], self.features), self.param_dtype)
        y = memory_efficient_dot(x.astype(self.dtype),
                                 kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        return y
