"""Tiled linear — rebuild of deepspeed/runtime/zero/tiling.py:26,255.

The reference splits a huge Linear into in/out tile grids so ZeRO-3 can
fetch and free slices of the weight independently, shrinking the working
set. On TPU, the equivalent working-set control is remat + sharding
constraints per tile; the module exists both for API parity and because
tiling is still useful to bound VMEM/HBM pressure for pathological layer
shapes (e.g. huge vocab projections).
"""

from typing import Any, Callable, Optional

import jax.numpy as jnp
import flax.linen as nn


def split_dim(total, splits):
    """Partition `total` into `splits` near-equal chunk sizes (reference
    tiling.py partition logic)."""
    base = total // splits
    rem = total - base * splits
    return [base + (1 if i < rem else 0) for i in range(splits)]


class TiledLinear(nn.Module):
    """Linear(in_features → out_features) computed as an
    in_splits × out_splits grid of sub-linears.

    Matches the reference semantics: input is split along its feature dim;
    each output tile sums contributions from every input tile; bias only on
    the (0, j) tiles. Gradients/ZeRO treat each tile as an independent
    parameter (the point of the exercise).
    """
    in_features: int
    out_features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    input_is_already_split: bool = False

    @nn.compact
    def __call__(self, x):
        assert self.in_features % 1 == 0
        in_sizes = split_dim(self.in_features, self.in_splits)
        out_sizes = split_dim(self.out_features, self.out_splits)

        if self.input_is_already_split:
            x_tiles = list(x)
        else:
            assert x.shape[-1] == self.in_features, (
                f"input feature dim {x.shape[-1]} != {self.in_features}")
            offsets = [0]
            for s in in_sizes:
                offsets.append(offsets[-1] + s)
            x_tiles = [x[..., offsets[i]:offsets[i + 1]]
                       for i in range(self.in_splits)]

        outs = []
        for j, out_sz in enumerate(out_sizes):
            acc = None
            for i in range(self.in_splits):
                y = nn.Dense(out_sz,
                             use_bias=(self.use_bias and i == 0),
                             dtype=self.dtype,
                             param_dtype=self.param_dtype,
                             kernel_init=self.kernel_init,
                             name=f"tile_{i}_{j}")(x_tiles[i])
                acc = y if acc is None else acc + y
            outs.append(acc)
        return jnp.concatenate(outs, axis=-1)


class TiledLinearReturnBias(TiledLinear):
    """Variant returning (output, None) for Megatron-style callers that
    expect a separate bias return (reference tiling.py:255)."""

    @nn.compact
    def __call__(self, x):
        return super().__call__(x), None
