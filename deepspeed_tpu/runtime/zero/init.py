"""Sharded parameter construction — rebuild of
deepspeed/runtime/zero/partition_parameters.py:183-261,265 (`zero.Init`) and
:1002 (`GatheredParameters`).

The reference monkey-patches ``nn.Module.__init__`` / ``torch.empty`` so
parameters are partitioned the moment they are constructed — required
because eager torch would otherwise materialize the full model on one GPU.
On TPU the same guarantee comes from jitting the *initializer* with sharded
output: each device materializes only its shard of each parameter; the full
tensor never exists anywhere. No monkey-patching, no ds_tensor bookkeeping.

    with zero.Init(mesh=mesh, zero_stage=3):
        params = zero.Init.current().init(model, rng, example_input)

or functionally::

    params = sharded_init(model, rng, example, mesh, stage=3)

`GatheredParameters(params)` yields the fully-replicated tree (the
reference's allgather context for e.g. weight export) and re-shards on exit.
"""

import contextlib
from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
from deepspeed_tpu.parallel import mesh as mesh_lib


def sharded_init(model, rng, example_input, mesh, stage=3, tp_specs=None,
                 param_persistence_threshold=0, layer_stacked_prefixes=()):
    """Initialize a flax model with every parameter born sharded.

    Two-phase: ``jax.eval_shape`` discovers shapes without allocating, the
    partitioner assigns specs, then the real init runs under jit with those
    specs as out_shardings — XLA emits per-device shard initialization only.
    """
    import jax.numpy as jnp
    example_input = jnp.asarray(example_input)

    shapes = jax.eval_shape(lambda r, x: model.init(r, x), rng, example_input)
    params_shapes = shapes["params"] if "params" in shapes else shapes
    part = ZeroPartitioner(mesh, stage, tp_specs=tp_specs,
                           param_persistence_threshold=param_persistence_threshold)
    part.layer_stacked_prefixes = tuple(layer_stacked_prefixes)
    shardings = part.param_shardings(params_shapes)

    @jax.jit
    def _init(r, x):
        variables = model.init(r, x)
        return variables["params"] if "params" in variables else variables

    with mesh:
        init_fn = jax.jit(
            lambda r, x: _init(r, x), out_shardings=shardings)
        params = init_fn(rng, example_input)
    return params, shardings


class Init:
    """Context-manager shell for API parity with ``deepspeed.zero.Init``
    (partition_parameters.py:265). Inside the context, `init()` builds
    sharded params; the context itself carries the mesh/stage config."""

    _current: Optional["Init"] = None

    def __init__(self, module=None, mesh=None, zero_stage=3, tp_specs=None,
                 remote_device=None, pin_memory=False, config=None,
                 param_persistence_threshold=0, enabled=True):
        self.mesh = mesh
        self.zero_stage = zero_stage if enabled else 0
        self.tp_specs = tp_specs
        self.param_persistence_threshold = param_persistence_threshold
        self.enabled = enabled
        # reference accepts a module to convert eagerly; we defer to init()
        self.module = module
        self.shardings = None

    @classmethod
    def current(cls):
        return cls._current

    def __enter__(self):
        Init._current = self
        return self

    def __exit__(self, *exc):
        Init._current = None
        return False

    def init(self, model, rng, example_input):
        if not self.enabled or self.mesh is None:
            variables = model.init(rng, example_input)
            return variables.get("params", variables)
        params, self.shardings = sharded_init(
            model, rng, example_input, self.mesh, stage=self.zero_stage,
            tp_specs=self.tp_specs,
            param_persistence_threshold=self.param_persistence_threshold)
        return params


@contextlib.contextmanager
def GatheredParameters(params, mesh=None, modifier_rank=None, fwd_module=None,
                       enabled=True):
    """Yield the fully-gathered (replicated) parameter tree — reference
    partition_parameters.py:1002. Mutations inside the context are NOT
    propagated back (functional world); callers re-shard explicitly with
    `jax.device_put` if they want to adopt edits."""
    if not enabled:
        yield params
        return
    gathered = jax.tree_util.tree_map(
        lambda p: jax.device_get(p) if hasattr(p, "sharding") else p, params)
    yield gathered
