"""Progressive Layer Drop — rebuild of
deepspeed/runtime/progressive_layer_drop.py:5.

theta(t) = (1 - theta_base) * exp(-gamma * t) + theta_base, fed to the model
as a per-step keep probability (the reference passes
``progressive_layer_drop=pld`` into forward kwargs, engine.py:1018-1019).
Here `theta_at` is jnp-traceable so it evaluates inside the jitted step.
"""

import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def theta_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        return (1.0 - self.theta) * jnp.exp(-self.gamma * step) + self.theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        self.current_theta = float(self.theta_at(global_step))
