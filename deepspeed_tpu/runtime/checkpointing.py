"""Sharded checkpoint save/load — rebuild of the reference's checkpoint
machinery (engine.py:1562-1891): tag directories, a ``latest`` pointer file,
model-states / optim-states file split, and client-state passthrough.

Format: each tag directory holds
  - ``mp_rank_00_model_states.npz``   — model params (reference engine.py:1837)
  - ``zero_pp_rank_{r}_mp_rank_00_optim_states.npz`` — optimizer + scaler
    state for data-parallel rank r (reference engine.py:1883 per-rank ZeRO
    shards). In the GSPMD world a single process holds all addressable
    shards, so r is ``jax.process_index()``.
  - ``meta.json`` — counters, lr-scheduler state, client state.

Arrays are stored flat with '/'-joined tree paths as npz keys and re-nested
on load. fp32 master weights live in the params tree itself, so the
``zero_to_fp32`` offline merge (reference utils/zero_to_fp32.py:70) reduces
to `load_tree` + `merge_zero_shards` below.
"""

import json
import os

import numpy as np
import jax

LATEST_FILE = "latest"


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix[:-1]] = np.asarray(jax.device_get(tree))
    return out


def _unflatten(flat):
    root = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def save_tree(path, tree):
    np.savez(path, **_flatten(tree))


def load_tree(path):
    with np.load(path, allow_pickle=False) as data:
        return _unflatten({k: data[k] for k in data.files})


def save_checkpoint(save_dir, tag, state, extra, save_latest=True, zero_stage=0):
    ckpt_dir = os.path.join(save_dir, str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)
    rank = jax.process_index()

    if rank == 0:
        save_tree(os.path.join(ckpt_dir, "mp_rank_00_model_states.npz"),
                  {"params": state.params})
    optim_tree = {
        "opt_state": state.opt_state,
        "scaler": state.scaler,
        "global_step": state.global_step,
        "skipped_steps": state.skipped_steps,
    }
    save_tree(os.path.join(
        ckpt_dir, f"zero_pp_rank_{rank}_mp_rank_00_optim_states.npz"), optim_tree)

    if rank == 0:
        meta = dict(extra)
        meta["zero_stage"] = zero_stage
        meta["world_size"] = jax.process_count()
        with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
            json.dump(meta, f, default=str)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        # ship the recovery script with every checkpoint (reference
        # engine.py:1873-1881 copies utils/zero_to_fp32.py alongside)
        try:
            import shutil
            from deepspeed_tpu.utils import zero_to_fp32 as _z2f
            shutil.copyfile(_z2f.__file__,
                            os.path.join(save_dir, "zero_to_fp32.py"))
        except Exception:
            pass


def read_latest_tag(load_dir):
    latest_path = os.path.join(load_dir, LATEST_FILE)
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            return f.read().strip()
    return None


def load_checkpoint(load_dir, tag=None):
    """Returns ({params, opt_state, scaler, global_step, skipped_steps},
    meta) or None if nothing to load (reference engine.py:1600 warns and
    returns None)."""
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            return None
    ckpt_dir = os.path.join(load_dir, str(tag))
    model_path = os.path.join(ckpt_dir, "mp_rank_00_model_states.npz")
    if not os.path.isfile(model_path):
        return None
    state = load_tree(model_path)
    rank = jax.process_index()
    optim_path = os.path.join(
        ckpt_dir, f"zero_pp_rank_{rank}_mp_rank_00_optim_states.npz")
    if not os.path.isfile(optim_path):
        optim_path = os.path.join(ckpt_dir, "zero_pp_rank_0_mp_rank_00_optim_states.npz")
    optim = load_tree(optim_path)
    state.update(optim)
    meta_path = os.path.join(ckpt_dir, "meta.json")
    meta = {}
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    for key in ("global_steps", "micro_steps", "global_samples", "skipped_steps"):
        if key in meta:
            try:
                meta[key] = int(meta[key])
            except (TypeError, ValueError):
                pass
    return state, meta


def merge_zero_shards(ckpt_dir):
    """Offline ZeRO-shard merge: the `zero_to_fp32.py` analog (reference
    utils/zero_to_fp32.py:70). With npz full-tree shards per process this
    concatenates nothing for single-host saves and simply returns the fp32
    params; kept as the stable entry point for multi-host shard merging."""
    model_path = os.path.join(ckpt_dir, "mp_rank_00_model_states.npz")
    return load_tree(model_path)["params"]
