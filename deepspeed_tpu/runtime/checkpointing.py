"""Sharded checkpoint save/load — rebuild of the reference's checkpoint
machinery (engine.py:1562-1891): tag directories, a ``latest`` pointer file,
model-states / optim-states file split, client-state passthrough, and — the
ZeRO property that matters at scale — **per-rank shard files** (reference
``zero_pp_rank_*`` shards, engine.py:1883) so no process ever materializes
the full optimizer state.

Format: each tag directory holds, per process r:
  - ``model_states_shard_{r}.npz``  — this process's addressable,
    replica-0 pieces of the param tree
  - ``optim_states_shard_{r}.npz``  — same for optimizer + scaler +
    counters
  - ``shard_index_{r}.json``        — for every piece: its tree path,
    npz key, global array shape/dtype, and the global index window it
    covers
and (rank 0 only) ``meta.json`` + the ``latest`` pointer + a copy of
``zero_to_fp32.py`` (reference engine.py:1873-1881).

Loading reads the union of all index files, so the shard layout at load
time is independent of the layout at save time: a dp=4 save restores onto
a dp=2 mesh (or a single host) by assembling exactly the index windows
each new shard needs — the reference's elastic restore
(zero/stage1.py:898-1031) expressed as window reads. With target shardings
supplied, assembly happens through ``jax.make_array_from_callback`` and
each process touches only the bytes of its own shards.

The r1 single-file format (``mp_rank_00_model_states.npz`` +
``zero_pp_rank_{r}_mp_rank_00_optim_states.npz``) is still read for
backward compatibility.
"""

import json
import os

import numpy as np
import jax

LATEST_FILE = "latest"


# ---------------------------------------------------------------- tree walk

def _walk(tree, prefix=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk(v, f"{prefix}{k}/")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _walk(v, f"{prefix}{i}/")
    elif tree is None:
        # empty pytree slot (e.g. the hierarchical comm path's
        # uncompressed buckets carry None error entries): nothing to
        # serialize — np.asarray(None) would pickle an object array that
        # np.load(allow_pickle=False) then refuses. The structure owner
        # rebuilds the Nones on load (engine._restore_error_lists).
        return
    else:
        yield prefix[:-1], tree


def _flatten(tree, prefix=""):
    return {p: np.asarray(jax.device_get(v)) for p, v in _walk(tree, prefix)}


def _unflatten(flat):
    root = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def save_tree(path, tree):
    np.savez(path, **_flatten(tree))


def load_tree(path):
    with np.load(path, allow_pickle=False) as data:
        return _unflatten({k: data[k] for k in data.files})


# ---------------------------------------------------------------- sharded IO

def _local_pieces(leaf):
    """Yield (piece_array, start, stop) for this process's replica-0 shards
    of `leaf` (whole-array for plain numpy / process-local values)."""
    is_global_jax = isinstance(leaf, jax.Array) \
        and hasattr(leaf, "addressable_shards") \
        and not (jax.process_count() > 1 and leaf.is_fully_addressable)
    if is_global_jax:
        for sh in leaf.addressable_shards:
            if sh.replica_id != 0:
                continue
            idx = sh.index  # tuple of slices into the global shape
            start = [0 if s.start is None else int(s.start) for s in idx]
            stop = [int(leaf.shape[d]) if s.stop is None else int(s.stop)
                    for d, s in enumerate(idx)]
            yield np.asarray(sh.data), start, stop
    else:
        # plain numpy, or a PROCESS-LOCAL jax array in a multi-process job
        # (fully addressable on every process — each process would claim a
        # replica-0 full window and double-cover the leaf): rank 0's value
        # is saved, like the reference's rank-criteria model save
        # (engine.py:508-524)
        arr = np.asarray(leaf)
        if jax.process_index() == 0:
            yield arr, [0] * arr.ndim, list(arr.shape)


def _save_sharded_trees(ckpt_dir, trees):
    """trees: {file_stem: pytree}. Writes this process's pieces + index."""
    rank = jax.process_index()
    index = {}
    for stem, tree in trees.items():
        pieces = {}
        for path, leaf in _walk(tree):
            entries = []
            for j, (arr, start, stop) in enumerate(_local_pieces(leaf)):
                key = f"{path}//{j}"
                # store raw bytes: npz cannot round-trip ml_dtypes arrays
                # (bfloat16 comes back as void '|V2'); shape+dtype live in
                # the index
                pieces[key] = np.frombuffer(
                    np.ascontiguousarray(arr).tobytes(), np.uint8)
                entries.append({"key": key, "start": start, "stop": stop})
            dt = leaf.dtype if hasattr(leaf, "dtype") \
                else np.asarray(leaf).dtype
            index[f"{stem}:{path}"] = {
                "file": f"{stem}_shard_{rank}.npz",
                "shape": list(np.shape(leaf)),
                "dtype": str(np.dtype(dt)),   # 'bfloat16' via ml_dtypes
                "pieces": entries,
            }
        np.savez(os.path.join(ckpt_dir, f"{stem}_shard_{rank}.npz"), **pieces)
    with open(os.path.join(ckpt_dir, f"shard_index_{rank}.json"), "w") as f:
        json.dump(index, f)


class ShardedCheckpoint:
    """Reader over the union of all ranks' shard index files."""

    def __init__(self, ckpt_dir):
        self.ckpt_dir = ckpt_dir
        self.leaves = {}   # "stem:path" -> {shape, dtype, pieces:[...]}
        self._files = {}
        found = False
        for fname in sorted(os.listdir(ckpt_dir)):
            if not (fname.startswith("shard_index_") and
                    fname.endswith(".json")):
                continue
            found = True
            with open(os.path.join(ckpt_dir, fname)) as f:
                for full, info in json.load(f).items():
                    entry = self.leaves.setdefault(full, {
                        "shape": tuple(info["shape"]),
                        "dtype": np.dtype(info["dtype"]),
                        "pieces": []})
                    for p in info["pieces"]:
                        entry["pieces"].append(
                            {"file": info["file"], **p})
        if not found:
            raise FileNotFoundError(f"no shard_index_*.json in {ckpt_dir}")

    def _piece(self, file, key, dtype, shape):
        if file not in self._files:
            self._files[file] = np.load(
                os.path.join(self.ckpt_dir, file), allow_pickle=False)
        raw = self._files[file][key]
        return np.frombuffer(raw.tobytes(), dtype).reshape(shape)

    def close(self):
        for f in self._files.values():
            f.close()
        self._files = {}

    def struct(self, stem):
        """Nested dict of jax.ShapeDtypeStruct for one tree."""
        flat = {}
        pre = f"{stem}:"
        for full, info in self.leaves.items():
            if full.startswith(pre):
                flat[full[len(pre):]] = jax.ShapeDtypeStruct(
                    info["shape"], info["dtype"])
        return _unflatten(flat)

    def _read_window(self, info, idx):
        """Assemble the region `idx` (tuple of slices) of one leaf from
        whichever pieces overlap it."""
        shape = info["shape"]
        start = [0 if s.start is None else int(s.start) for s in idx]
        stop = [shape[d] if s.stop is None else int(s.stop)
                for d, s in enumerate(idx)]
        out = np.empty([b - a for a, b in zip(start, stop)],
                       info["dtype"])
        filled = 0
        for p in info["pieces"]:
            inter_a = [max(a, pa) for a, pa in zip(start, p["start"])]
            inter_b = [min(b, pb) for b, pb in zip(stop, p["stop"])]
            if any(a >= b for a, b in zip(inter_a, inter_b)):
                continue
            src = self._piece(p["file"], p["key"], info["dtype"],
                              [b - a for a, b in zip(p["start"], p["stop"])])
            src_sl = tuple(slice(a - pa, b - pa) for a, pa, b in
                           zip(inter_a, p["start"], inter_b))
            dst_sl = tuple(slice(a - sa, b - sa) for a, sa, b in
                           zip(inter_a, start, inter_b))
            out[dst_sl] = src[src_sl]
            filled += int(np.prod([b - a for a, b in zip(inter_a, inter_b)]))
        # pieces never overlap (each came from a distinct replica-0 shard
        # window), so full coverage <=> the element counts add up; anything
        # less means a rank's shard/index files are missing and resuming
        # would read uninitialized memory
        if filled != out.size:
            why = "missing" if filled < out.size \
                else "duplicated (stale save generations?)"
            raise IOError(
                f"checkpoint window inconsistent: assembled {filled} of "
                f"{out.size} elements — shard files in {self.ckpt_dir} "
                f"are {why}")
        return out

    def assemble(self, stem, shardings=None):
        """Rebuild one tree. With `shardings` (pytree of jax shardings
        matching struct(stem)): each process reads only the windows of its
        own addressable shards via make_array_from_callback. Without:
        plain full numpy assembly (single-host convenience)."""
        struct = self.struct(stem)
        flat_sh = dict(_walk(shardings)) if shardings is not None else {}

        def build(path):
            info = self.leaves[f"{stem}:{path}"]
            sh = flat_sh.get(path)
            if sh is None:
                return self._read_window(
                    info, tuple(slice(0, s) for s in info["shape"]))
            return jax.make_array_from_callback(
                tuple(info["shape"]), sh,
                lambda idx, info=info: self._read_window(info, idx))

        flat = {p: build(p) for p, _ in _walk(struct)}
        return _unflatten(flat)


# ---------------------------------------------------------------- public API

def _sync(label):
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(label)


def commit_dir_swap(stage_dir, final_dir, fault_point=None):
    """THE two-rename publish protocol, shared by the blocking save and
    the elastic snapshot commit (runtime/elastic/snapshot.py): move the
    existing final dir aside, swap the finished staging dir in, drop
    the old one. A crash anywhere in the window leaves either the old
    tag or ``{tag}.old`` on disk, never a half-written final dir —
    ``resolve_ckpt_dir`` (and resume's candidate walk) find the
    survivor. ``fault_point`` names the injection hook fired between
    the two renames (the fault-injection suite's crash window)."""
    import shutil
    if fault_point:
        # import OUTSIDE the rename window: an ImportError between the
        # renames would manufacture the half-committed state this
        # protocol exists to avoid
        from deepspeed_tpu.runtime.elastic import faults as _faults
    old_dir = final_dir + ".old"
    shutil.rmtree(old_dir, ignore_errors=True)
    if os.path.isdir(final_dir):
        os.rename(final_dir, old_dir)
    if fault_point:
        _faults.fire(fault_point, tag=os.path.basename(final_dir))
    os.rename(stage_dir, final_dir)
    shutil.rmtree(old_dir, ignore_errors=True)


def save_checkpoint(save_dir, tag, state, extra, save_latest=True,
                    zero_stage=0):
    final_dir = os.path.join(save_dir, str(tag))
    # write into a staging directory and swap in at the end: re-saving an
    # existing tag must neither mix shard generations (world-size changes
    # leave stale higher-rank files whose windows would double-cover) nor
    # destroy the previous valid save if the job dies mid-write
    ckpt_dir = final_dir + ".saving"
    rank = jax.process_index()
    if rank == 0:
        import shutil
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        os.makedirs(ckpt_dir, exist_ok=True)
    _sync(f"ckpt_stage:{tag}")

    _save_sharded_trees(ckpt_dir, {
        "model_states": {"params": state.params},
        "optim_states": {
            "opt_state": state.opt_state,
            "scaler": state.scaler,
            "global_step": state.global_step,
            "skipped_steps": state.skipped_steps,
        },
    })

    # loaders need EVERY rank's shard files, so the swap-in (and the
    # `latest` pointer) must not happen until all ranks finished writing
    # (the reference's tag-consistency barrier, engine.py:1745-1760)
    _sync(f"ckpt_save:{tag}")

    if rank == 0:
        import shutil
        meta = dict(extra)
        meta["zero_stage"] = zero_stage
        meta["world_size"] = jax.process_count()
        with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
            json.dump(meta, f, default=str)
        commit_dir_swap(ckpt_dir, final_dir,
                        fault_point="ckpt_between_renames")
        ckpt_dir = final_dir
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
        # ship the recovery script with every checkpoint (reference
        # engine.py:1873-1881 copies utils/zero_to_fp32.py alongside)
        try:
            import shutil
            from deepspeed_tpu.utils import zero_to_fp32 as _z2f
            shutil.copyfile(_z2f.__file__,
                            os.path.join(save_dir, "zero_to_fp32.py"))
        except Exception:
            pass


def read_latest_tag(load_dir):
    latest_path = os.path.join(load_dir, LATEST_FILE)
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            return f.read().strip()
    return None


def resolve_ckpt_dir(load_dir, tag):
    """Directory for `tag`, falling back to the `{tag}.old` staging name: a
    crash between save_checkpoint's two renames leaves the only valid save
    at `{tag}.old`, and a restart must find it rather than silently train
    from scratch."""
    final_dir = os.path.join(load_dir, str(tag))
    if not os.path.isdir(final_dir) and os.path.isdir(final_dir + ".old"):
        return final_dir + ".old"
    return final_dir


def _load_meta(ckpt_dir):
    meta_path = os.path.join(ckpt_dir, "meta.json")
    meta = {}
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    for key in ("global_steps", "micro_steps", "global_samples",
                "skipped_steps"):
        if key in meta:
            try:
                meta[key] = int(meta[key])
            except (TypeError, ValueError):
                pass
    return meta


def load_checkpoint(load_dir, tag=None, shardings_fn=None,
                    load_optimizer=True):
    """Returns ({params, opt_state, scaler, global_step, skipped_steps},
    meta) or None if nothing to load (reference engine.py:1600 warns and
    returns None).

    shardings_fn(struct) -> matching tree of jax shardings (or None): when
    given and the checkpoint is in the sharded format, each process reads
    only its own shard windows. `struct` has the same {"params":...,
    "opt_state":..., ...} layout with ShapeDtypeStruct leaves.

    load_optimizer=False skips reading the opt_state shards entirely
    (typically 2x the parameter bytes of disk IO) — the returned tree has
    opt_state={}; callers doing module-only restores substitute their live
    optimizer state.
    """
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            return None
    ckpt_dir = resolve_ckpt_dir(load_dir, tag)
    try:
        reader = ShardedCheckpoint(ckpt_dir)
    except (FileNotFoundError, NotADirectoryError):
        return _load_checkpoint_legacy(ckpt_dir)

    if not load_optimizer:
        for full in list(reader.leaves):
            if full.startswith("optim_states:opt_state/"):
                del reader.leaves[full]

    struct = dict(reader.struct("model_states"))
    struct.update(reader.struct("optim_states"))
    shardings = shardings_fn(struct) if shardings_fn is not None else None

    def sub(tree, key):
        return None if tree is None else tree.get(key)

    state = {"params": reader.assemble(
        "model_states", {"params": sub(shardings, "params")})["params"]}
    optim_sh = None
    if shardings is not None:
        optim_sh = {k: shardings.get(k) for k in
                    ("opt_state", "scaler", "global_step", "skipped_steps")
                    if k in struct}
    state.update(reader.assemble("optim_states", optim_sh))
    state.setdefault("opt_state", {})
    reader.close()
    return state, _load_meta(ckpt_dir)


def _load_checkpoint_legacy(ckpt_dir):
    """r1 format: full-tree npz per rank."""
    model_path = os.path.join(ckpt_dir, "mp_rank_00_model_states.npz")
    if not os.path.isfile(model_path):
        return None
    state = load_tree(model_path)
    rank = jax.process_index()
    optim_path = os.path.join(
        ckpt_dir, f"zero_pp_rank_{rank}_mp_rank_00_optim_states.npz")
    if not os.path.isfile(optim_path):
        optim_path = os.path.join(
            ckpt_dir, "zero_pp_rank_0_mp_rank_00_optim_states.npz")
    state.update(load_tree(optim_path))
    return state, _load_meta(ckpt_dir)


def merge_zero_shards(ckpt_dir):
    """Offline ZeRO-shard merge: the `zero_to_fp32.py` analog (reference
    utils/zero_to_fp32.py:70) — assembles the full fp32 param tree from
    every rank's shard files."""
    try:
        reader = ShardedCheckpoint(ckpt_dir)
        params = reader.assemble("model_states")["params"]
        reader.close()
        return params
    except FileNotFoundError:
        model_path = os.path.join(ckpt_dir, "mp_rank_00_model_states.npz")
        return load_tree(model_path)["params"]
