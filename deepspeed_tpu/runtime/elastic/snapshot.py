"""Async snapshots through the swap tier (ISSUE 7 tentpole).

``engine.save_checkpoint`` is a blocking save: d2h every leaf,
``np.savez`` every shard, fence, rename. A preemption-tolerant job
needs checkpoints cheap enough to take every few minutes, so the
:class:`AsyncSnapshotter` splits the save into two halves that bracket
a training step:

- ``begin(tag, trees)`` — each leaf's replica-0 pieces are copied into
  host staging buffers (the step-time cost: a d2h + memcpy + crc32 per
  leaf) and submitted as ``async_pwrite`` batches on a DEDICATED
  write-behind aio handle — the swap tier's write-handle pattern
  (``PartitionedParamSwapper``, PR 5), deliberately NOT its handle:
  ``aio_handle_wait`` drains a whole handle, so a shared stream would
  let the next unpark's drain fence absorb the snapshot writes after
  ~0 overlap (and the snapshot fence absorb the parks). Leaves that
  already rest on NVMe arrive as :class:`FileLeaf` markers: their
  bytes are read straight from the swap file (page-cache warm — the
  park just wrote them) and re-queued, never re-serialized from the
  device. ``begin`` returns immediately; the disk writes overlap the
  NEXT training step.
- ``finalize()`` — the drain fence (``handle.wait()``; by the next
  step boundary the writes have had a whole step to land, so the fence
  usually measures ~0), the config-gated ``fsync`` pass, the
  checksummed index + manifest, and the commit: the two-rename
  protocol from runtime/checkpointing.py (``tag.saving`` swaps in,
  ``tag.old`` keeps the previous generation alive through the window).

The manifest is the commit point: a snapshot directory without a
parseable manifest whose per-file crc32s match is NOT a snapshot
(``SnapshotReader`` raises :class:`SnapshotCorrupt`, and
``resume.load_latest_valid`` falls back to the newest tag that
verifies). Elastic restore reuses the window-read machinery of
``runtime/checkpointing.py``: the index records each piece's global
index window, so a save at dp=W re-assembles under any dp=W' target
shardings.
"""

import json
import os
import shutil
import time
import zlib

import numpy as np

from deepspeed_tpu.runtime import checkpointing as ckpt
from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.utils.logging import logger

MANIFEST = "manifest.json"
FORMAT = "dstpu-elastic-1"


class SnapshotError(IOError):
    pass


class SnapshotCorrupt(SnapshotError):
    """The snapshot fails validation (torn manifest, missing file,
    checksum mismatch) — callers fall back to an older snapshot."""


class FileLeaf:
    """A leaf whose bytes already rest in a file on the snapshot
    filesystem (a parked NVMe swap file): the snapshotter reads the
    file instead of re-serializing a device array."""

    def __init__(self, path, shape, dtype):
        self.path = path
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)


def _crc(buf):
    return zlib.crc32(buf) & 0xFFFFFFFF


def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def is_snapshot_dir(path):
    return os.path.isfile(os.path.join(path, MANIFEST))


def has_snapshots(snapshot_dir):
    """Whether ``snapshot_dir`` holds ANY committed snapshot — by
    scanning, not the ``latest`` pointer (a crash before the
    first-ever pointer write leaves a valid committed tag with no
    pointer, and loaders must still find it)."""
    try:
        names = os.listdir(snapshot_dir)
    except OSError:
        return False
    return any(is_snapshot_dir(os.path.join(snapshot_dir, n))
               for n in names)


def _registry():
    from deepspeed_tpu.telemetry import default_registry
    return default_registry()


def _recorder():
    from deepspeed_tpu.telemetry import default_recorder
    return default_recorder()


class AsyncSnapshotter:
    """See module docstring. One instance per engine; at most one
    snapshot in flight (the engine finalizes at the next step boundary
    before beginning another)."""

    def __init__(self, snapshot_dir, aio_config=None, write_handle=None,
                 fsync=True, keep=2, registry=None, recorder=None):
        self.dir = str(snapshot_dir)
        os.makedirs(self.dir, exist_ok=True)
        if write_handle is None:
            from deepspeed_tpu.runtime.swap_tensor.swapper import (
                _make_aio_handle)
            write_handle = _make_aio_handle(aio_config)
        self._handle = write_handle
        self.fsync = bool(fsync)
        self.keep = max(int(keep), 1)
        self._registry = registry
        self._recorder = recorder
        self._inflight = None

    def _reg(self):
        if self._registry is None:
            self._registry = _registry()
        return self._registry

    def _rec(self):
        if self._recorder is None:
            self._recorder = _recorder()
        return self._recorder

    @property
    def in_flight(self):
        return self._inflight is not None

    # ------------------------------------------------------------ begin
    def begin(self, tag, trees, extra=None, meta=None):
        """Stage + submit the async writes for one snapshot.

        ``trees``: ``{stem: pytree}`` (the checkpointing.py layout —
        ``model_states``/``optim_states``). Leaves may be jax arrays,
        numpy arrays, or :class:`FileLeaf` markers. ``extra`` lands in
        the manifest under ``"extra"`` (counters, client state);
        ``meta`` merges into the manifest top level (world sizes,
        batch triangle). Returns the staged byte count."""
        assert self._inflight is None, "snapshot already in flight"
        import jax
        rank = jax.process_index()
        final_dir = os.path.join(self.dir, str(tag))
        stage_dir = final_dir + ".saving"
        if rank == 0:
            shutil.rmtree(stage_dir, ignore_errors=True)
            os.makedirs(stage_dir, exist_ok=True)
        ckpt._sync(f"snapshot_stage:{tag}")

        t0 = time.perf_counter()
        files = {}     # fname -> {"crc32", "nbytes"}
        leaves = {}    # "stem:path" -> {"shape", "dtype", "pieces"}
        fds, bufs, sizes = [], [], []
        seq = 0
        total = 0
        from_files = 0
        try:
            for stem, tree in trees.items():
                for path, leaf in ckpt._walk(tree):
                    entries = []
                    for arr, start, stop, src in self._pieces(leaf):
                        fname = f"{stem}_r{rank}_{seq:05d}.bin"
                        seq += 1
                        if getattr(self._handle, "direct_active", False):
                            from deepspeed_tpu.ops.native.aio import \
                                aligned_empty
                            buf = aligned_empty(arr.nbytes)
                        else:
                            buf = np.empty(arr.nbytes, np.uint8)
                        np.copyto(buf, arr.view(np.uint8).reshape(-1))
                        # open through the handle so the aio.o_direct
                        # knob applies here too (the snapshot fsync
                        # price was page-cache-masked without it);
                        # finalize truncates direct files back to the
                        # exact byte count, keeping the on-disk format
                        # (np.fromfile + crc over nbytes) unchanged
                        fd = self._handle.open_fd(
                            os.path.join(stage_dir, fname),
                            os.O_WRONLY | os.O_CREAT, 0o644) \
                            if hasattr(self._handle, "open_fd") else \
                            os.open(os.path.join(stage_dir, fname),
                                    os.O_WRONLY | os.O_CREAT, 0o644)
                        self._handle.async_pwrite(buf, fd)
                        fds.append(fd)
                        sizes.append(buf.nbytes)
                        bufs.append(buf)   # alive until the drain fence
                        files[fname] = {"crc32": _crc(buf),
                                        "nbytes": buf.nbytes}
                        entries.append({"file": fname, "start": start,
                                        "stop": stop})
                        total += buf.nbytes
                        from_files += src == "swapfile"
                    shape, dtype = _leaf_shape_dtype(leaf)
                    leaves[f"{stem}:{path}"] = {
                        "shape": shape, "dtype": dtype, "pieces": entries}
        except Exception:
            # mid-loop failure (short swap file, ENOSPC, EMFILE) with
            # writes already submitted: the aio threads must not keep
            # writing from buffers this frame is about to drop — drain,
            # close, remove the staging dir, THEN unwind
            try:
                self._handle.wait()
            except Exception:
                pass
            for fd in fds:
                try:
                    os.close(fd)
                except OSError:
                    pass
            shutil.rmtree(stage_dir, ignore_errors=True)
            raise
        reg = self._reg()
        reg.counter("ckpt/bytes_written").inc(total)
        reg.counter("ckpt/snapshots").inc()
        self._rec().record("ckpt_begin", tag=str(tag), files=seq,
                           bytes=total, from_swapfiles=from_files,
                           stage_s=time.perf_counter() - t0)
        self._inflight = {
            "tag": str(tag), "stage": stage_dir, "final": final_dir,
            "fds": fds, "bufs": bufs, "sizes": sizes,
            "files": files, "leaves": leaves,
            "bytes": total, "extra": dict(extra or {}),
            "meta": dict(meta or {}), "t_begin": t0,
        }
        return total

    @staticmethod
    def _pieces(leaf):
        """Yield (host uint8-viewable array, start, stop, source) for
        one leaf — FileLeaf bytes come off the swap file (no device
        readback), everything else goes through the checkpointing
        replica-0 piece walk (which pays the d2h)."""
        if isinstance(leaf, FileLeaf):
            # parked swap files only exist for fully-addressable leaves
            # (the park path d2h's whole arrays), so every process holds
            # an identical copy — rank 0 claims the full window, exactly
            # like ckpt._local_pieces' process-local rule (a per-rank
            # claim would double-cover and fail the load's coverage
            # check)
            import jax
            if jax.process_index() != 0:
                return
            raw = np.fromfile(leaf.path, np.uint8)
            want = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
            if raw.nbytes < want:
                raise SnapshotError(
                    f"swap file {leaf.path} holds {raw.nbytes} bytes, "
                    f"leaf needs {want}")
            yield raw[:want], [0] * len(leaf.shape), list(leaf.shape), \
                "swapfile"
            return
        for arr, start, stop in ckpt._local_pieces(leaf):
            yield np.ascontiguousarray(arr), start, stop, "staged"

    # --------------------------------------------------------- finalize
    def finalize(self):
        """Drain fence → fsync (gated) → checksummed index + manifest →
        two-rename commit → latest pointer + pruning. Returns
        ``(final_dir, stall_s)`` where ``stall_s`` is the host seconds
        this call actually blocked on the drain."""
        inf = self._inflight
        assert inf is not None, "no snapshot in flight"
        self._inflight = None
        import jax
        rank = jax.process_index()
        try:
            t0 = time.perf_counter()
            self._handle.wait()   # the drain fence — inside the try:
            stall = time.perf_counter() - t0   # an aio write error
            from deepspeed_tpu.ops.native.aio import fd_is_direct
            while inf["fds"]:     # must hit the fd-closing except path
                fd = inf["fds"][-1]    # peek: a raising fsync/close
                if fd_is_direct(fd):   # leaves the fd for the except
                    # direct writes landed page-aligned; restore the
                    # exact byte count the loader/crc expects (the
                    # fsync below is metadata-only here — the data is
                    # already on device, which is the honest price cut)
                    os.ftruncate(fd, inf["sizes"][len(inf["fds"]) - 1])
                if self.fsync:         # path's cleanup loop
                    os.fsync(fd)
                os.close(fd)
                inf["fds"].pop()
            index_name = f"files_index_{rank}.json"
            index_path = os.path.join(inf["stage"], index_name)
            index_doc = {"files": inf["files"], "leaves": inf["leaves"]}
            index_bytes = json.dumps(index_doc).encode()
            with open(index_path, "wb") as fh:
                fh.write(index_bytes)
                if self.fsync:
                    fh.flush()
                    os.fsync(fh.fileno())
            ckpt._sync(f"snapshot_save:{inf['tag']}")
            if rank == 0:
                self._commit(inf, index_name, index_bytes)
            self._rec().record(
                "ckpt_commit", tag=inf["tag"], bytes=inf["bytes"],
                wait_s=stall, fsync=self.fsync,
                total_s=time.perf_counter() - inf["t_begin"])
        except faults.SimulatedCrash:
            raise          # a simulated crash leaves the disk as-is
        except Exception as e:
            # a REAL failure (ENOSPC, I/O error) must not leak fds
            # across retries — close what the commit loop hadn't
            # reached; the staging dir stays for the orphan sweep
            for fd in inf["fds"]:
                try:
                    os.close(fd)
                except OSError:
                    pass
            inf["fds"] = []
            self._rec().record("ckpt_abort", tag=inf["tag"],
                               reason=repr(e))
            raise
        return inf["final"], stall

    def _commit(self, inf, index_name, index_bytes):
        """Rank-0 commit: manifest into staging, fsync, then the
        two-rename swap (checkpointing.py's protocol: a crash in this
        window leaves either the previous tag or ``tag.old`` on disk,
        never a half-written final directory)."""
        # in the multi-process shape every rank contributes an index
        # file; rank 0 records each one's checksum so validation covers
        # the whole set (a missing rank's shards must fail the load)
        import jax
        indexes = {index_name: {"crc32": _crc(index_bytes),
                                "nbytes": len(index_bytes)}}
        for r in range(jax.process_count()):
            name = f"files_index_{r}.json"
            if name in indexes:
                continue
            with open(os.path.join(inf["stage"], name), "rb") as fh:
                b = fh.read()
            indexes[name] = {"crc32": _crc(b), "nbytes": len(b)}
        manifest = {
            "format": FORMAT,
            "tag": inf["tag"],
            "ts": time.time(),
            "bytes": inf["bytes"],
            "index_files": indexes,
            "extra": inf["extra"],
            **inf["meta"],
        }
        man_path = os.path.join(inf["stage"], MANIFEST)
        with open(man_path, "w") as fh:
            json.dump(manifest, fh, default=str)
            if self.fsync:
                fh.flush()
                os.fsync(fh.fileno())
        if self.fsync:
            # the staging dir's ENTRIES must be durable before the
            # rename publishes them: data fds fsynced + dirents lost to
            # power loss would leave a "committed" snapshot that fails
            # validation — the exact loss the fsync contract prevents
            _fsync_path(inf["stage"])
        ckpt.commit_dir_swap(inf["stage"], inf["final"],
                             fault_point="snapshot_between_renames")
        if self.fsync:
            _fsync_path(self.dir)   # the renames themselves
        with open(os.path.join(self.dir, ckpt.LATEST_FILE), "w") as fh:
            fh.write(inf["tag"])
            if self.fsync:
                fh.flush()
                os.fsync(fh.fileno())
        self._prune(keep_tag=inf["tag"])

    def _prune(self, keep_tag):
        """Retire committed snapshots beyond ``keep`` (newest first by
        commit time; the just-committed tag always survives)."""
        tags = []
        for name in os.listdir(self.dir):
            path = os.path.join(self.dir, name)
            if name.endswith((".saving", ".old")) or name == keep_tag:
                continue
            if os.path.isdir(path) and is_snapshot_dir(path):
                tags.append((os.path.getmtime(path), path))
        tags.sort(reverse=True)
        for _, path in tags[self.keep - 1:]:
            shutil.rmtree(path, ignore_errors=True)
            shutil.rmtree(path + ".old", ignore_errors=True)

    def abort(self, reason="abort"):
        """Drop an in-flight snapshot: drain (aio must not complete
        into freed buffers), close fds, remove the staging dir."""
        inf = self._inflight
        if inf is None:
            return
        self._inflight = None
        try:
            self._handle.wait()
        except Exception:
            pass
        for fd in inf["fds"]:
            try:
                os.close(fd)
            except OSError:
                pass
        shutil.rmtree(inf["stage"], ignore_errors=True)
        self._rec().record("ckpt_abort", tag=inf["tag"], reason=reason)


def _leaf_shape_dtype(leaf):
    if isinstance(leaf, FileLeaf):
        return list(leaf.shape), str(leaf.dtype)
    dt = leaf.dtype if hasattr(leaf, "dtype") \
        else np.asarray(leaf).dtype  # sync-ok: dtype probe of host scalar
    return list(np.shape(leaf)), str(np.dtype(dt))


# ------------------------------------------------------------------ reader

class SnapshotReader(ckpt.ShardedCheckpoint):
    """Validating reader over one committed snapshot directory.
    Inherits the window-read assembly (``struct``/``assemble``) from
    :class:`ShardedCheckpoint` — the piece index windows make a dp=W
    save loadable under any dp=W' target shardings — and replaces the
    npz piece source with the snapshot's raw ``.bin`` shards.

    ``verify=True`` (the default) checks every index file and data
    shard against the manifest's crc32s up front, so a torn manifest,
    a missing rank, or a rotted shard surfaces as
    :class:`SnapshotCorrupt` BEFORE any state is assembled."""

    def __init__(self, snap_dir, verify=True):
        self.ckpt_dir = snap_dir
        self.leaves = {}
        self._files = {}
        man_path = os.path.join(snap_dir, MANIFEST)
        try:
            with open(man_path) as fh:
                self.manifest = json.load(fh)
        except OSError as e:
            raise SnapshotCorrupt(f"no manifest in {snap_dir}: {e}")
        except ValueError as e:
            raise SnapshotCorrupt(f"torn manifest in {snap_dir}: {e}")
        if self.manifest.get("format") != FORMAT:
            raise SnapshotCorrupt(
                f"unknown snapshot format "
                f"{self.manifest.get('format')!r} in {snap_dir}")
        self._file_meta = {}
        for name, info in (self.manifest.get("index_files") or {}).items():
            try:
                with open(os.path.join(snap_dir, name), "rb") as fh:
                    raw = fh.read()
            except OSError as e:
                raise SnapshotCorrupt(f"missing index {name}: {e}")
            if verify and (_crc(raw) != info["crc32"]
                           or len(raw) != info["nbytes"]):
                raise SnapshotCorrupt(f"index {name} fails checksum")
            try:
                doc = json.loads(raw)
            except ValueError as e:
                raise SnapshotCorrupt(f"torn index {name}: {e}")
            self._file_meta.update(doc.get("files", {}))
            for full, info_l in doc.get("leaves", {}).items():
                entry = self.leaves.setdefault(full, {
                    "shape": tuple(info_l["shape"]),
                    "dtype": np.dtype(info_l["dtype"]),
                    "pieces": []})
                for p in info_l["pieces"]:
                    entry["pieces"].append(dict(p, key=None))
        if not self.leaves:
            raise SnapshotCorrupt(f"snapshot {snap_dir} indexes no leaves")
        if verify:
            self.verify_files()

    def verify_files(self):
        """Streaming crc pass over every data shard — bounded memory
        (one 4 MB chunk at a time), no caching: a >RAM-scale snapshot
        must verify without holding checkpoint-bytes + assembled
        arrays simultaneously."""
        for name, info in self._file_meta.items():
            path = os.path.join(self.ckpt_dir, name)
            crc, nbytes = 0, 0
            try:
                with open(path, "rb") as fh:
                    while True:
                        chunk = fh.read(1 << 22)
                        if not chunk:
                            break
                        crc = zlib.crc32(chunk, crc)
                        nbytes += len(chunk)
            except OSError as e:
                raise SnapshotCorrupt(f"missing shard {name}: {e}")
            if nbytes != info["nbytes"] \
                    or (crc & 0xFFFFFFFF) != info["crc32"]:
                raise SnapshotCorrupt(f"shard {name} fails checksum")

    def _piece(self, file, key, dtype, shape):
        # lazy per-file cache: only shards this load's windows actually
        # touch are read (each holds exactly one piece)
        raw = self._files.get(file)
        if raw is None:
            raw = np.fromfile(os.path.join(self.ckpt_dir, file), np.uint8)
            self._files[file] = raw
        try:
            return raw.view(dtype).reshape(shape)     # zero-copy
        except ValueError:
            return np.frombuffer(raw.tobytes(), dtype).reshape(shape)

    def close(self):
        self._files = {}

    def state_and_meta(self, shardings_fn=None, load_optimizer=True):
        """Assemble the full train-state tree (the layout
        engine.load_checkpoint adopts) + the manifest meta. With
        ``load_optimizer=False`` the opt_state leaves (typically 2x the
        parameter bytes) are dropped from the index before assembly, so
        their shard files are never read — module-only restores
        substitute the caller's live optimizer state."""
        if not load_optimizer:
            for full in list(self.leaves):
                if full.startswith("optim_states:opt_state/"):
                    del self.leaves[full]
        struct = dict(self.struct("model_states"))
        struct.update(self.struct("optim_states"))
        shardings = shardings_fn(struct) if shardings_fn is not None \
            else None

        def sub(key):
            return None if shardings is None else shardings.get(key)

        state = {"params": self.assemble(
            "model_states", {"params": sub("params")})["params"]}
        optim_sh = None
        if shardings is not None:
            optim_sh = {k: shardings.get(k) for k in
                        ("opt_state", "scaler", "global_step",
                         "skipped_steps") if k in struct}
        state.update(self.assemble("optim_states", optim_sh))
        state.setdefault("opt_state", {})
        meta = {k: v for k, v in self.manifest.items()
                if k not in ("index_files",)}
        for key in ("global_steps", "micro_steps", "global_samples",
                    "skipped_steps"):
            if key in meta.get("extra", {}):
                try:
                    meta["extra"][key] = int(meta["extra"][key])
                except (TypeError, ValueError):
                    pass
        return state, meta
