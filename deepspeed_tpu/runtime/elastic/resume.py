"""Elastic resume (ISSUE 7): load a snapshot written at dp world size
W into an engine running at dp W'.

Two mechanisms compose:

- **state re-sharding** is free: the snapshot index records each
  piece's global window (runtime/checkpointing.py's elastic-restore
  machinery), so assembly under the new engine's
  ``ZeroPartitioner``-derived shardings reads exactly the windows each
  new shard needs — ZeRO-1/2/3 partitions re-shape to W' without a
  gather;
- **the batch triangle** is re-solved by the elasticity HCN ladder
  (elasticity/elasticity.py): with an ``elasticity`` config block, the
  engine's own config already recomputed micro/grad-accum for W' such
  that ``micro * gas * W' == final_batch_size`` — this module VERIFIES
  the snapshot was written under the same effective batch, so the loss
  trajectory continues as if the run were never interrupted.

``load_latest_valid`` is the recovery policy: newest committed
snapshot first (the ``latest`` pointer), then every older tag (and its
``.old`` crash-window sibling), skipping — and reporting, once per
recovery, through the watchdog — any candidate that fails manifest or
checksum validation.
"""

import os
import time

from deepspeed_tpu.runtime import checkpointing as ckpt
from deepspeed_tpu.runtime.elastic.snapshot import (
    MANIFEST, SnapshotCorrupt, SnapshotReader, is_snapshot_dir)
from deepspeed_tpu.utils.logging import logger


def _candidates(snapshot_dir):
    """Candidate snapshot directories, genuinely-newest first: ordered
    by commit mtime with the ``latest`` pointer only as a tie-breaker
    (the pointer is written AFTER the commit rename, so a crash in
    that window leaves it pointing one generation back while a newer
    valid snapshot sits on disk — mtime order still finds it). Each
    tag is followed by its ``.old`` sibling (the crash-between-renames
    fallback — same rule as checkpointing.resolve_ckpt_dir)."""
    latest = ckpt.read_latest_tag(snapshot_dir)
    latest_path = os.path.join(snapshot_dir, latest) if latest else None
    dated = []
    try:
        names = os.listdir(snapshot_dir)
    except OSError:
        return
    for name in names:
        path = os.path.join(snapshot_dir, name)
        if not os.path.isdir(path) or name.endswith((".saving", ".old")):
            continue
        dated.append((os.path.getmtime(path), path == latest_path, path))
    dated.sort(reverse=True)
    ordered = [p for _, _, p in dated]
    if latest_path is not None and latest_path not in ordered \
            and os.path.isdir(latest_path + ".old"):
        ordered.append(latest_path)   # only the .old sibling survives
    for path in ordered:
        if os.path.isdir(path):
            yield path
        if os.path.isdir(path + ".old"):
            yield path + ".old"


def load_latest_valid(snapshot_dir, shardings_fn=None, on_corrupt=None,
                      verify=True, load_optimizer=True):
    """Newest snapshot that validates, as ``(state_tree, meta)`` — or
    None when nothing under ``snapshot_dir`` is loadable. Invalid
    candidates invoke ``on_corrupt(path, exc)`` and are skipped."""
    for cand in _candidates(snapshot_dir):
        if not is_snapshot_dir(cand):
            continue
        try:
            reader = SnapshotReader(cand, verify=verify)
            state, meta = reader.state_and_meta(
                shardings_fn=shardings_fn, load_optimizer=load_optimizer)
            reader.close()
            meta["snapshot_dir"] = cand
            return state, meta
        except SnapshotCorrupt as e:
            logger.warning(f"snapshot {cand} invalid ({e}); "
                           f"falling back to an older one")
            if on_corrupt is not None:
                on_corrupt(cand, e)
    return None


def verify_elastic_batch(engine, meta):
    """The effective-batch contract: when the engine trains elastic,
    the snapshot's final batch size must match the engine's — the HCN
    ladder guarantees a compatible (micro, gas) exists for the new
    world size, and the engine's config already solved it."""
    snap_batch = meta.get("train_batch_size")
    if snap_batch is None:
        return
    if engine._config.elasticity_enabled:
        if int(snap_batch) != int(engine.train_batch_size()):
            raise SnapshotCorrupt(
                f"snapshot effective batch {snap_batch} != engine "
                f"{engine.train_batch_size()} — the elastic config "
                f"changed between save and resume")
    elif int(snap_batch) != int(engine.train_batch_size()):
        logger.warning(
            f"resuming a snapshot with effective batch {snap_batch} "
            f"into an engine with {engine.train_batch_size()} and no "
            f"elasticity block — the loss trajectory will diverge "
            f"from the original run")


def elastic_resume(engine, snapshot_dir, tag=None, load_module_only=False,
                   load_optimizer_states=True,
                   load_lr_scheduler_states=True):
    """Restore ``engine`` from the newest valid snapshot under
    ``snapshot_dir`` (or the specific ``tag``). Returns
    ``(tag, client_state)`` like ``engine.load_checkpoint``, or None
    when there is nothing to resume from. The load flags carry the
    load_checkpoint semantics: module-only restores keep the engine's
    live optimizer state and counters untouched by the scheduler.

    Corrupt candidates are skipped with exactly one flight-recorder
    dump per recovery (the watchdog's latched ``ckpt_corrupt`` rule);
    a successful load re-arms it."""
    t0 = time.perf_counter()
    corrupt_seen = []

    def on_corrupt(path, exc):
        rec = engine.flight_recorder
        rec.record("ckpt_corrupt", dir=path, reason=repr(exc))
        if engine.watchdog is not None and not corrupt_seen:
            engine.watchdog.note_ckpt_corrupt(path, repr(exc))
        corrupt_seen.append(path)

    # orphaned staging dirs come in two flavors, told apart by whether
    # the manifest made it in (finalize writes it LAST, just before the
    # renames):
    # - manifest present → the process died inside the COMMIT (the
    #   two-rename window): a genuine incident, reported once through
    #   the latched watchdog rule;
    # - no manifest → a snapshot was merely in flight when the process
    #   stopped (clean exit mid-interval, preemption without grace) —
    #   expected lifecycle, a ring event but no dump.
    # Both are cleared now that they are recorded: an uncommitted
    # .saving dir is never adopted, and leaving it would re-report on
    # every restart (each restart's fresh watchdog has a fresh latch).
    import shutil
    sp = getattr(engine, "_snapshotter", None)
    live = sp._inflight["stage"] if sp is not None and sp.in_flight \
        else None
    stale_staging = []
    try:
        for name in sorted(os.listdir(snapshot_dir)):
            path = os.path.join(snapshot_dir, name)
            # never sweep the calling engine's own LIVE in-flight
            # snapshot (aio writes may be landing in it right now)
            if name.endswith(".saving") and path != live:
                stale_staging.append(path)
    except OSError:
        pass
    for path in stale_staging:
        if is_snapshot_dir(path):
            on_corrupt(path, SnapshotCorrupt(
                "interrupted commit: staging dir left behind"))
        else:
            engine.flight_recorder.record(
                "ckpt_orphan", dir=path,
                reason="snapshot in flight at process exit")
        shutil.rmtree(path, ignore_errors=True)

    shardings_fn = None if engine._offload_cfg.enabled \
        else engine._ckpt_shardings
    # module-only restores substitute the engine's live optimizer state
    # — skip assembling the (2x param bytes) opt_state shards entirely,
    # unless there is no live state to substitute (mirrors
    # engine.load_checkpoint's want_opt rule)
    want_opt = (load_optimizer_states and not load_module_only) \
        or engine.state is None
    if tag is not None:
        cand = ckpt.resolve_ckpt_dir(snapshot_dir, tag)
        loaded = None
        if is_snapshot_dir(cand):
            try:
                reader = SnapshotReader(cand)
                loaded = reader.state_and_meta(shardings_fn=shardings_fn,
                                               load_optimizer=want_opt)
                reader.close()
            except SnapshotCorrupt as e:
                on_corrupt(cand, e)
        if loaded is None:
            loaded = load_latest_valid(snapshot_dir,
                                       shardings_fn=shardings_fn,
                                       on_corrupt=on_corrupt,
                                       load_optimizer=want_opt)
    else:
        loaded = load_latest_valid(snapshot_dir, shardings_fn=shardings_fn,
                                   on_corrupt=on_corrupt,
                                   load_optimizer=want_opt)
    if loaded is None:
        if engine.watchdog is not None and not corrupt_seen:
            engine.watchdog.note_ckpt_ok()
        return None
    state_tree, meta = loaded
    verify_elastic_batch(engine, meta)
    extra = dict(meta.get("extra") or {})
    keep_live_opt = load_module_only or not load_optimizer_states
    engine._adopt_ckpt_tree(state_tree, extra,
                            keep_live_opt=keep_live_opt,
                            load_lr=load_lr_scheduler_states)
    if engine.watchdog is not None:
        engine.watchdog.note_ckpt_ok()
    from_dp = meta.get("dp_world_size")
    engine.flight_recorder.record(
        "resume", tag=meta.get("tag"), step=engine.global_steps,
        from_dp=from_dp, to_dp=engine.dp_world_size,
        micro=engine.train_micro_batch_size_per_gpu(),
        grad_accum=engine.gradient_accumulation_steps(),
        fell_back=len(corrupt_seen),
        load_s=time.perf_counter() - t0)
    if from_dp is not None and int(from_dp) != engine.dp_world_size:
        logger.info(
            f"elastic resume: dp {from_dp} -> {engine.dp_world_size}, "
            f"micro={engine.train_micro_batch_size_per_gpu()}, "
            f"gas={engine.gradient_accumulation_steps()}, effective "
            f"batch {engine.train_batch_size()} preserved")
    return meta.get("tag"), extra.get("client_state", {})
