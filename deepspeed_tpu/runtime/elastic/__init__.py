"""Elastic fault-tolerant training (ISSUE 7 + ISSUE 15) — the
reference's ``elasticity/`` module grown into a runtime fault-tolerance
subsystem:

- ``snapshot``: periodic ASYNC checkpoints whose shard writes ride the
  swap tier's dedicated write-behind aio handle; the drain fence + a
  checksummed manifest is the commit point (the two-rename protocol
  from runtime/checkpointing.py), so the step-time cost of a snapshot
  is a host memcpy, not an fsync;
- ``preemption``: SIGTERM hook → final snapshot within a grace budget,
  with ``preempt`` events in the flight recorder;
- ``resume``: load a snapshot written at dp world size W into W' —
  shard windows re-assemble through the ZeroPartitioner plans and the
  elasticity HCN ladder re-solves micro/grad-accum so the effective
  batch (and the loss trajectory) is preserved;
- ``faults``: the deterministic fault-injection harness the tests
  drive end-to-end (kill-at-step, SIGKILL, in-collective hang, torn
  manifest, rotted checksum, crash-between-renames);
- ``hang`` (ISSUE 15): the collective hang watchdog — a daemon thread
  that converts a collective blocked past
  ``fault_tolerance.hang_deadline_s`` into one latched ``rank_dead``
  dump + a distinct ``EXIT_HANG`` exit, and writes the per-rank
  heartbeat file the supervisor monitors;
- ``supervisor`` (ISSUE 15): the launcher-level supervisor — spawn the
  world, watch liveness + heartbeats, tear down survivors on any rank
  death, restart the HCN-valid shrunk world from the latest valid
  snapshot with jittered backoff, bounded by ``max_restarts``.

Resolution is lazy (PEP 562, like the package root): ``faults``,
``hang`` and ``supervisor`` are stdlib-side and must stay importable in
a launcher process that never initializes a jax backend (libtpu takes
an exclusive per-process lock — launcher/runner.py:_local_chip_count),
while ``snapshot``/``resume`` legitimately import jax.
"""

from deepspeed_tpu.utils.lazy import lazy_attrs

_LAZY = {
    "AsyncSnapshotter": ("deepspeed_tpu.runtime.elastic.snapshot",
                         "AsyncSnapshotter"),
    "FileLeaf": ("deepspeed_tpu.runtime.elastic.snapshot", "FileLeaf"),
    "SnapshotCorrupt": ("deepspeed_tpu.runtime.elastic.snapshot",
                        "SnapshotCorrupt"),
    "SnapshotError": ("deepspeed_tpu.runtime.elastic.snapshot",
                      "SnapshotError"),
    "SnapshotReader": ("deepspeed_tpu.runtime.elastic.snapshot",
                       "SnapshotReader"),
    "is_snapshot_dir": ("deepspeed_tpu.runtime.elastic.snapshot",
                        "is_snapshot_dir"),
    "PreemptionHandler": ("deepspeed_tpu.runtime.elastic.preemption",
                          "PreemptionHandler"),
    "elastic_resume": ("deepspeed_tpu.runtime.elastic.resume",
                       "elastic_resume"),
    "load_latest_valid": ("deepspeed_tpu.runtime.elastic.resume",
                          "load_latest_valid"),
    "HangWatchdog": ("deepspeed_tpu.runtime.elastic.hang",
                     "HangWatchdog"),
    "EXIT_HANG": ("deepspeed_tpu.runtime.elastic.hang", "EXIT_HANG"),
    "Supervisor": ("deepspeed_tpu.runtime.elastic.supervisor",
                   "Supervisor"),
    "EXIT_CRASH_LOOP": ("deepspeed_tpu.runtime.elastic.supervisor",
                        "EXIT_CRASH_LOOP"),
    # submodules resolved as attributes (`elastic.faults.fire(...)`)
    "faults": ("deepspeed_tpu.runtime.elastic.faults", None),
    "hang": ("deepspeed_tpu.runtime.elastic.hang", None),
    "supervisor": ("deepspeed_tpu.runtime.elastic.supervisor", None),
    "snapshot": ("deepspeed_tpu.runtime.elastic.snapshot", None),
    "preemption": ("deepspeed_tpu.runtime.elastic.preemption", None),
    "resume": ("deepspeed_tpu.runtime.elastic.resume", None),
}

__all__ = sorted(_LAZY)

__getattr__, __dir__ = lazy_attrs(__name__, _LAZY)
