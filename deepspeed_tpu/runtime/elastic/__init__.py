"""Elastic preemption-tolerant training (ISSUE 7) — the reference's
``elasticity/`` module grown into a runtime fault-tolerance subsystem:

- ``snapshot``: periodic ASYNC checkpoints whose shard writes ride the
  swap tier's dedicated write-behind aio handle; the drain fence + a
  checksummed manifest is the commit point (the two-rename protocol
  from runtime/checkpointing.py), so the step-time cost of a snapshot
  is a host memcpy, not an fsync;
- ``preemption``: SIGTERM hook → final snapshot within a grace budget,
  with ``preempt`` events in the flight recorder;
- ``resume``: load a snapshot written at dp world size W into W' —
  shard windows re-assemble through the ZeroPartitioner plans and the
  elasticity HCN ladder re-solves micro/grad-accum so the effective
  batch (and the loss trajectory) is preserved;
- ``faults``: the deterministic fault-injection harness the tests
  drive end-to-end (kill-at-step, torn manifest, rotted checksum,
  crash-between-renames).
"""

from deepspeed_tpu.runtime.elastic import faults  # stdlib-only, no cycle
from deepspeed_tpu.runtime.elastic.snapshot import (
    AsyncSnapshotter,
    FileLeaf,
    SnapshotCorrupt,
    SnapshotError,
    SnapshotReader,
    is_snapshot_dir,
)
from deepspeed_tpu.runtime.elastic.preemption import PreemptionHandler
from deepspeed_tpu.runtime.elastic.resume import (
    elastic_resume,
    load_latest_valid,
)

__all__ = [
    "AsyncSnapshotter", "FileLeaf", "SnapshotCorrupt", "SnapshotError",
    "SnapshotReader", "is_snapshot_dir", "PreemptionHandler",
    "elastic_resume", "load_latest_valid", "faults",
]
