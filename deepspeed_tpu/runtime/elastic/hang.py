"""Collective hang watchdog + per-rank heartbeat (ISSUE 15).

A hard rank death (SIGKILL, OOM, node loss) leaves every SURVIVOR
blocked forever inside its next cross-process collective: on this
backend the collectives execute synchronously inside the step-dispatch
call, so the survivor's main thread parks in C with no Python signal
delivery and no timeout. Nothing inside the process can unblock it —
but a daemon thread can still OBSERVE it, because the blocked
collective holds no GIL.

:class:`HangWatchdog` is that thread. The engine brackets every region
that can block on a peer — the step dispatch (the same interval the
``train/host_step_s`` blocked-in-dispatch accounting already measures,
ISSUE 12) and the boundary exchanges (cluster allgather, preemption
agreement, snapshot commit fence) — with ``enter_dispatch(kind,
step)`` / ``exit_dispatch()``: two plain attribute stores, no lock, no
syscall. The daemon thread polls; a region blocked past
``fault_tolerance.hang_deadline_s`` becomes:

1. one ``rank_hang`` flight-recorder event + one LATCHED ``rank_dead``
   watchdog dump (telemetry/anomaly.py) carrying the ring history that
   led into the stall;
2. ``os._exit(EXIT_HANG)`` — a DISTINCT exit code, because a normal
   exit path would run atexit hooks and jax teardown that themselves
   block on the dead collective. The supervisor
   (runtime/elastic/supervisor.py) reads the code as "a peer of this
   rank is gone/stuck", tears the world down and restarts it shrunk.

The FIRST guarded region of each kind gets ``first_deadline_factor``
(10×) the deadline instead of it: it contains the XLA compile (minutes
on a cold cache), which is a stall with a progress bar, not a hang —
but a peer that dies BEFORE this rank's first boundary region must
still be detected eventually, so the first occurrence is slack, never
exempt. From the second occurrence on, the plain deadline applies.

The same thread writes this rank's **heartbeat file**
(``<dir>/hb_rank<N>``) every ``heartbeat_interval_s``. The heartbeat
covers the failure the dispatch guard cannot: a process frozen as a
whole (SIGSTOP, a wedged interpreter) stops beating, and the
supervisor's staleness check catches it. Conversely an in-collective
hang KEEPS beating (the daemon thread is alive) — which is exactly why
the blocked-in-dispatch guard exists. The two detectors are
complementary, not redundant (docs/fault_tolerance.md has the matrix).

Stdlib-only on purpose: the supervisor imports this module for the
exit-code contract and must never pull jax into the launcher process
(libtpu takes an exclusive per-process lock — see
launcher/runner.py:_local_chip_count).
"""

import os
import threading
import time

# Distinct process exit code for "collective stalled past the hang
# deadline": the supervisor classifies it as a peer-loss incident
# (this rank is a healthy DETECTOR, not the casualty). 40-range to
# stay clear of shell (1/2/126/127) and signal (128+N) conventions.
EXIT_HANG = 43


def heartbeat_path(directory, rank):
    return os.path.join(directory, f"hb_rank{int(rank)}")


class HangWatchdog:
    """See module docstring. Construct-and-forget: the daemon thread
    starts immediately; ``stop()`` joins it (tests, clean shutdown —
    a production trip never returns)."""

    def __init__(self, deadline_s, poll_s=None, rank=0, world=1,
                 watchdog=None, recorder=None, registry=None,
                 heartbeat_dir=None, heartbeat_interval_s=1.0,
                 restart_epoch=0, exit_fn=None,
                 first_deadline_factor=10.0):
        assert deadline_s > 0, deadline_s
        self.deadline_s = float(deadline_s)  # sync-ok: host config scalar
        # poll fast enough that detection lands well inside
        # deadline + grace, slow enough to stay invisible in `top`
        self.poll_s = float(poll_s) if poll_s \
            else min(max(self.deadline_s / 10.0, 0.05),
                     1.0)  # sync-ok: host config scalar
        self.rank = int(rank)
        self.world = int(world)
        self.watchdog = watchdog
        self.recorder = recorder
        self.registry = registry
        self.heartbeat_dir = heartbeat_dir or None
        self.heartbeat_interval_s = float(
            heartbeat_interval_s)  # sync-ok: host config scalar
        self.restart_epoch = int(restart_epoch)
        self.first_deadline_factor = max(
            float(first_deadline_factor), 1.0)  # sync-ok: host cfg
        self._exit_fn = exit_fn if exit_fn is not None else os._exit
        self._dispatch = None        # (t_enter, kind, step, occurrence)
        self._counts = {}            # kind -> occurrences seen
        self.tripped = None          # detail dict once tripped
        self._stop = threading.Event()
        self._last_beat = 0.0
        if self.heartbeat_dir:
            os.makedirs(self.heartbeat_dir, exist_ok=True)
            self._beat()             # exists before the first poll
        self._thread = threading.Thread(
            target=self._loop, name="dstpu-hang-watchdog", daemon=True)
        self._thread.start()

    # ------------------------------------------------- engine-side marks
    # Plain attribute stores (GIL-atomic): these run once per step on
    # the hot path, so they must cost nothing measurable.

    def enter_dispatch(self, kind="step", step=None):
        n = self._counts.get(kind, 0) + 1
        self._counts[kind] = n
        self._dispatch = (time.monotonic(), kind, step, n)

    def exit_dispatch(self):
        self._dispatch = None

    # --------------------------------------------------------- the loop

    def _loop(self):
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            if self.heartbeat_dir and \
                    now - self._last_beat >= self.heartbeat_interval_s:
                self._beat()
            d = self._dispatch
            if d is None:
                continue
            t0, kind, step, occurrence = d
            # the first region of each kind bears the XLA compile:
            # slack (factor× deadline), never exempt — a peer dead
            # before OUR first boundary region must still be caught
            limit = self.deadline_s * (self.first_deadline_factor
                                       if occurrence <= 1 else 1.0)
            blocked = now - t0
            if blocked > limit:
                self._trip(kind, step, blocked, limit)
                return

    def _beat(self):
        self._last_beat = time.monotonic()
        try:
            with open(heartbeat_path(self.heartbeat_dir, self.rank),
                      "w") as fh:
                fh.write(f"{time.time()} {os.getpid()} "
                         f"{self.restart_epoch}\n")
        except OSError:
            pass                    # a torn hb dir must not kill training

    def _trip(self, kind, step, blocked_s, limit_s=None):
        """Latched conversion of an eternal hang into a reportable exit
        (runs exactly once — the thread returns after)."""
        limit_s = limit_s if limit_s is not None else self.deadline_s
        self.tripped = {"kind": kind, "step": step,
                        "blocked_s": blocked_s,
                        "deadline_s": limit_s,
                        "rank": self.rank,
                        "restart_epoch": self.restart_epoch}
        if self.recorder is not None:
            self.recorder.record(
                "rank_hang", rank=self.rank, step=step, region=kind,
                blocked_s=blocked_s, deadline_s=limit_s,
                restart_epoch=self.restart_epoch)
        if self.registry is not None:
            self.registry.counter("fault/hangs_detected").inc()
        if self.watchdog is not None:
            # the latched rank_dead dump: the ring history that led
            # into the stall, written by THIS rank (the survivor) —
            # the dead/hung peer can't write anything
            self.watchdog.note_rank_dead(
                rank=self.rank, reason="collective_hang", step=step,
                blocked_s=blocked_s, deadline_s=limit_s,
                restart_epoch=self.restart_epoch, world=self.world)
        # drop the heartbeat so the supervisor can't mistake the
        # window between our exit and its poll for a live rank
        self._remove_heartbeat()
        self._exit_fn(EXIT_HANG)

    def _remove_heartbeat(self):
        if self.heartbeat_dir:
            try:
                os.remove(heartbeat_path(self.heartbeat_dir, self.rank))
            except OSError:
                pass

    def stop(self, remove_heartbeat=True):
        self._stop.set()
        self._thread.join(timeout=5.0)
        if remove_heartbeat:
            self._remove_heartbeat()
