"""Launcher-level fault-tolerance supervisor (ISSUE 15).

PR 7 made a *graceful* SIGTERM survivable (final snapshot inside the
grace budget, elastic resume at a different world size). Everything
harder — ``kill -9``, OOM, node loss, a rank wedged inside a
collective — still ended the job: the dead rank's peers block forever
inside gloo and nothing restarts them. This module closes that gap,
the training-side twin of PR 11's replica-pool recovery:

- :class:`Supervisor` spawns the world over the launcher env contract
  (the same ``DSTPU_*`` rendezvous variables launcher/launch.py and
  the PR-10 ``spawn_workers`` harness use, plus ``DSTPU_HEARTBEAT_DIR``
  and ``DSTPU_RESTART_EPOCH``), then monitors two signals:

  1. **child liveness** — a nonzero/killed exit is a rank death; the
     distinct ``EXIT_HANG`` code (runtime/elastic/hang.py) marks a
     HEALTHY rank that detected a peer stuck in a collective;
  2. **heartbeat staleness** — each rank's hang-watchdog thread
     rewrites ``hb_rank<N>`` every ``heartbeat_interval_s``; a file
     gone stale past ``heartbeat_stale_s`` means the whole process
     froze (SIGSTOP, wedged interpreter) without exiting.

- on any incident it **tears down the survivors** (SIGTERM, then
  SIGKILL after ``grace_kill_s`` — a rank blocked inside a dead
  collective never runs its Python SIGTERM handler, and a rank parked
  in ``time.sleep`` swallows it via the PreemptionHandler's flag-only
  handler + PEP 475 retry, so the escalation is mandatory, not
  polish), clears the heartbeat files, and **restarts the shrunk
  world**: the next world size comes from the elasticity HCN ladder's
  valid chip counts (``valid_worlds_from_elasticity``), so the
  respawned engines' configs re-solve micro/grad-accum for W' and
  PR 7's ``load_latest_valid``/``elastic_resume`` (snapshot
  ``auto_resume``) continues the loss trajectory step-for-step.

- restarts are **bounded**: jittered exponential backoff between
  epochs, and after ``max_restarts`` incidents the supervisor writes
  exactly one latched ``crash_loop`` watchdog dump and exits
  ``EXIT_CRASH_LOOP`` — a world that dies every epoch must page a
  human, not spin.

Every transition lands in the flight recorder (``supervisor_spawn``,
``rank_exit``, ``world_down``, ``restart``, ``crash_loop``) stamped
with the ``restart_epoch``, so ``telemetry/view.py`` renders the
die → detect → shrink → resume timeline from the supervisor's dump
next to the workers' own ``rank_hang``/``resume`` events.

This module must stay importable WITHOUT touching a jax backend: it
runs in the launcher process, and on a TPU-VM libtpu takes an
exclusive per-process lock (see launcher/runner.py:_local_chip_count)
— a supervisor that initialized a backend would starve every worker it
spawns. Imports are stdlib + the jax-free telemetry/elasticity planes.
"""

import os
import random
import signal
import socket
import subprocess
import sys
import time

from deepspeed_tpu.runtime.elastic.hang import (EXIT_HANG,
                                                heartbeat_path)
from deepspeed_tpu.utils.distributed import jittered_backoff
from deepspeed_tpu.utils.logging import logger

# the supervisor's own terminal exit: restart budget exhausted (or no
# feasible world remains) — distinct from any worker code
EXIT_CRASH_LOOP = 44


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def solve_next_world(world, lost, valid_worlds=None, min_world=1):
    """The shrink policy: lose ``lost`` ranks, keep the largest world
    the elasticity ladder can still batch for.

    Returns the next world size, or None when nothing >= ``min_world``
    is feasible (the supervisor treats that as terminal). Without a
    ``valid_worlds`` list any size >= ``min_world`` is acceptable —
    and a world already at the floor retries AT the floor (transient
    single-host failures should not kill a 1-host job; the
    ``max_restarts`` bound is what stops a deterministic crash)."""
    target = world - max(int(lost), 1)
    if valid_worlds is None:
        return max(target, min_world)
    cands = sorted({int(w) for w in valid_worlds
                    if min_world <= int(w)})
    below = [w for w in cands if w <= target]
    if below:
        return below[-1]
    # nothing fits the shrunk target: retry at the largest valid size
    # that the CURRENT world could run (in-place retry — the failure
    # may be transient; the restart budget bounds the loop)
    at_or_below = [w for w in cands if w <= world]
    return at_or_below[-1] if at_or_below else None


def valid_worlds_from_elasticity(param_dict, local_devices=1,
                                 roles=None):
    """Valid PROCESS counts for a ds-config with an ``elasticity``
    block: the HCN ladder's valid chip counts divided by the chips
    each process owns. Returns None (no constraint) when the block is
    absent/disabled — the supervisor then shrinks arithmetically.

    ISSUE 18: a serving ``roles`` map (rank -> role name) contributes
    the DECODE-COUNT ladder — every world that keeps all non-decode
    ranks plus at least one decode rank is feasible, because losing a
    decode rank only shrinks D (the router rank is positional rank 0
    and the respawned world re-balances the ledger onto the
    survivors). When both constraints apply they intersect; an empty
    intersection returns None (terminal, by design loud)."""
    from deepspeed_tpu import elasticity as el
    worlds = None
    if el.elasticity_enabled(param_dict):
        _final, valid_chips = el.compute_elastic_config(param_dict)
        n = max(int(local_devices), 1)
        worlds = sorted({c // n for c in valid_chips
                         if c % n == 0 and c >= n}) or None
    if roles:
        n_fixed = sum(1 for name in roles.values()
                      if str(name) != "decode")
        # every world keeping the fixed (non-decode) ranks + >= 1
        # decode rank, up to the configured full complement
        ladder = list(range(max(n_fixed + 1, 2), len(roles) + 1))
        if worlds is None:
            worlds = ladder or None
        else:
            worlds = sorted(set(worlds) & set(ladder)) or None
    return worlds


class Supervisor:
    """See module docstring. ``cmd`` is the full worker argv (e.g.
    ``[sys.executable, "train.py", ...]``); the supervisor adds only
    environment, never arguments, so any script the PR-10
    ``spawn_workers`` harness could run is supervisable unchanged."""

    def __init__(self, cmd, world, *,
                 heartbeat_dir, min_world=1, valid_worlds=None,
                 hang_deadline_s=300.0, heartbeat_interval_s=1.0,
                 heartbeat_stale_s=None, grace_kill_s=5.0,
                 max_restarts=3, backoff_base_s=0.5, backoff_max_s=30.0,
                 poll_s=0.1, coordinator_addr="127.0.0.1",
                 local_devices=None, env=None, cwd=None, log_dir=None,
                 rendezvous_retries=None, rendezvous_backoff_s=None,
                 dump_dir=None, watchdog=None, recorder=None,
                 registry=None, seed=0, roles=None):
        assert cmd, "need a worker command"
        assert world >= 1, world
        self.cmd = [str(c) for c in cmd]
        self.world = int(world)
        self.min_world = int(min_world)
        self.valid_worlds = list(valid_worlds) if valid_worlds else None
        self.heartbeat_dir = str(heartbeat_dir)
        self.hang_deadline_s = float(hang_deadline_s)  # sync-ok: host cfg
        self.heartbeat_interval_s = float(
            heartbeat_interval_s)  # sync-ok: host config scalar
        # staleness must tolerate a worker whose beat thread is starved
        # by a GIL-holding compile — tie the default to the hang
        # deadline, not the beat interval
        self.heartbeat_stale_s = float(heartbeat_stale_s) \
            if heartbeat_stale_s is not None \
            else self.hang_deadline_s \
            + 3 * self.heartbeat_interval_s  # sync-ok: host cfg
        self.grace_kill_s = float(grace_kill_s)  # sync-ok: host cfg
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)  # sync-ok: host cfg
        self.backoff_max_s = float(backoff_max_s)  # sync-ok: host cfg
        self.poll_s = float(poll_s)  # sync-ok: host cfg
        self.coordinator_addr = coordinator_addr
        self.local_devices = local_devices
        self.env = dict(os.environ if env is None else env)
        self.cwd = cwd
        self.log_dir = log_dir or os.path.join(self.heartbeat_dir, "logs")
        self.rendezvous_retries = rendezvous_retries
        self.rendezvous_backoff_s = rendezvous_backoff_s
        if recorder is None:
            from deepspeed_tpu.telemetry.recorder import default_recorder
            recorder = default_recorder()
        self.recorder = recorder
        if registry is None:
            from deepspeed_tpu.telemetry.registry import default_registry
            registry = default_registry()
        self.registry = registry
        if watchdog is None and dump_dir:
            from deepspeed_tpu.telemetry.anomaly import Watchdog
            watchdog = Watchdog(dump_dir, recorder=self.recorder,
                                registry=self.registry,
                                source="supervisor")
        self.watchdog = watchdog
        # ISSUE 17: serving replica worlds are ROLE-ASSIGNED by rank
        # (0 = prefill+router, rest = decode). The supervisor exports
        # each rank's role (DSTPU_SERVING_ROLE) and stamps it into
        # rank_exit events/incidents, so a dead DECODE rank reads as
        # one in the die → respawn timeline. None = training world.
        self.roles = {int(r): str(name) for r, name in roles.items()} \
            if roles else None
        self._rng = random.Random(seed)
        self.restart_epoch = 0
        self.restarts = 0
        self.incidents = []          # one dict per detected incident
        self.log_paths = {}          # (epoch, rank) -> log file path
        self.procs = {}              # rank -> Popen (current epoch)
        self._logs_open = []
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        os.makedirs(self.log_dir, exist_ok=True)

    # ------------------------------------------------------------- spawn

    def roles_for_world(self, world, prefer=None):
        """Role map for a world of size ``world``. Roles are
        POSITIONAL (rank 0 = the router/prefill rank, every other
        rank = decode), so a shrunk or grown world RE-DERIVES the map
        instead of inheriting dead ranks' entries: each surviving
        rank keeps its configured role, ranks beyond the configured
        map get the majority non-rank-0 role (``"decode"`` for a
        serving world). None when this is a training world.

        ``prefer`` (ISSUE 19) biases the fill role for ranks BEYOND
        the configured map — the hook the windowed SLO plane's
        per-role recommendation (:func:`telemetry.slo.roles_signal`)
        drives: a grown world whose decode burn rate is hot fills new
        ranks with ``prefer="decode"`` instead of the historical
        majority. Configured ranks are never re-roled (their engines'
        ledgers and snapshots are role-shaped)."""
        if not self.roles:
            return None
        tail = [name for r, name in self.roles.items() if r != 0]
        fill = max(set(tail), key=tail.count) if tail else "decode"
        if prefer:
            fill = str(prefer)
        return {r: self.roles.get(r, fill) for r in range(int(world))}

    def roles_preference(self):
        """The SLO plane's fill-role bias for the NEXT respawn, read
        purely from ``slo/*`` gauges on the supervisor's registry
        (rank-0 exports them; a scraping supervisor mirrors them).
        Returns the role to prefer, or None when no role is hot —
        ``roles_for_world(world, prefer=self.roles_preference())`` is
        the ladder step."""
        if not self.roles:
            return None
        from deepspeed_tpu.telemetry.slo import roles_signal
        rec = roles_signal(self.registry)
        hot = sorted(r for r, a in rec.items() if a == "up")
        if not hot:
            return None
        # rank 0's role is pinned; preferring it cannot change the
        # fill — pick the first hot NON-rank-0-capable role instead
        rank0 = self.roles.get(0)
        tail_hot = [r for r in hot if r != rank0]
        return tail_hot[0] if tail_hot else hot[0]

    def _child_env(self, rank, world, port):
        env = dict(self.env)
        env.update({
            "DSTPU_COORDINATOR_ADDR": self.coordinator_addr,
            "DSTPU_COORDINATOR_PORT": str(port),
            "DSTPU_NUM_PROCESSES": str(world),
            "DSTPU_PROCESS_ID": str(rank),
            "DSTPU_HEARTBEAT_DIR": self.heartbeat_dir,
            "DSTPU_RESTART_EPOCH": str(self.restart_epoch),
        })
        env.pop("DSTPU_LOCAL_DEVICE_IDS", None)
        # roles re-derive per WORLD, not per configured map — a world
        # shrunk from D=2 to D=1 must still mark its rank 1 "decode";
        # the SLO plane's hot role (if any) biases the fill for ranks
        # beyond the configured map (ISSUE 19)
        roles = self.roles_for_world(world,
                                     prefer=self.roles_preference())
        if roles and rank in roles:
            env["DSTPU_SERVING_ROLE"] = roles[rank]
        if self.rendezvous_retries is not None:
            env["DSTPU_RENDEZVOUS_RETRIES"] = str(self.rendezvous_retries)
        if self.rendezvous_backoff_s is not None:
            env["DSTPU_RENDEZVOUS_BACKOFF_S"] = \
                str(self.rendezvous_backoff_s)
        if self.local_devices:
            # CPU-harness shape (the spawn_workers contract): N virtual
            # devices per process; a real TPU host ignores this. Any
            # inherited device-count flag is REPLACED — the parent's
            # harness count (e.g. conftest's 8) times the world would
            # otherwise inflate the global mesh
            import re
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                env.get("XLA_FLAGS", ""))
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{self.local_devices}").strip()
        return env

    def _spawn(self, world):
        port = _free_port()
        self.procs = {}
        for rank in range(world):
            log_path = os.path.join(
                self.log_dir,
                f"epoch{self.restart_epoch}_rank{rank}.log")
            self.log_paths[(self.restart_epoch, rank)] = log_path
            fh = open(log_path, "w")
            self._logs_open.append(fh)
            self.procs[rank] = subprocess.Popen(
                self.cmd, env=self._child_env(rank, world, port),
                cwd=self.cwd, stdout=fh, stderr=subprocess.STDOUT)
        self.recorder.record(
            "supervisor_spawn", world=world,
            restart_epoch=self.restart_epoch, port=port,
            pids=[p.pid for p in self.procs.values()])
        self.registry.gauge("fault/restart_epoch").set(self.restart_epoch)
        self.registry.gauge("fault/world_size").set(world)
        logger.info(f"[supervisor] epoch {self.restart_epoch}: spawned "
                    f"world={world} (coordinator :{port})")

    # ----------------------------------------------------------- monitor

    @staticmethod
    def _classify(rc):
        if rc == EXIT_HANG:
            return "hang_detected"
        if rc < 0:
            return f"signal:{-rc}"
        return f"exit:{rc}"

    def _stale_ranks(self, live):
        """Ranks whose heartbeat file exists but stopped moving. A
        worker that never wrote one (fault_tolerance off) is simply
        unmonitored — absence is not evidence of death."""
        now = time.time()
        stale = []
        for rank in live:
            path = heartbeat_path(self.heartbeat_dir, rank)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age > self.heartbeat_stale_s:
                stale.append((rank, age))
        return stale

    def _teardown(self, survivors):
        """SIGTERM → grace → SIGKILL → reap. The escalation is
        load-bearing: a survivor blocked inside a dead collective
        never runs a Python signal handler, and the engine's
        PreemptionHandler swallows SIGTERM into a flag (PEP 475
        restarts the interrupted sleep), so SIGTERM alone can strand
        both shapes forever."""
        t0 = time.time()
        alive = [p for p in survivors if p.poll() is None]
        if not alive:
            return    # nothing to tear down: the run()-exit sweep on a
            #           clean/already-reaped world must not feed a ~0s
            #           sample into the per-INCIDENT teardown histogram
        for p in alive:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.time() + self.grace_kill_s
        while time.time() < deadline and \
                any(p.poll() is None for p in alive):
            time.sleep(min(self.poll_s, 0.05))
        for p in alive:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in alive:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                logger.warning(f"[supervisor] pid {p.pid} survived "
                               f"SIGKILL reap window")
        self.registry.histogram("fault/teardown_s").observe(
            time.time() - t0)

    def _clear_heartbeats(self):
        try:
            names = os.listdir(self.heartbeat_dir)
        except OSError:
            return
        for name in names:
            if not name.startswith("hb_rank"):
                continue
            try:
                # per-file: one racing unlink (a straggler child dying
                # mid-sweep) must not abandon the rest — a stale file
                # left behind would fake a heartbeat_stale incident
                # against the NEXT epoch's healthy rank
                os.remove(os.path.join(self.heartbeat_dir, name))
            except OSError:
                pass

    def _close_logs(self):
        for fh in self._logs_open:
            try:
                fh.close()
            except OSError:
                pass
        self._logs_open = []

    def emergency_teardown(self, signum=None):
        """Public signal-time teardown: kill the world, clear the
        heartbeat files, close log handles — the one sequence both the
        supervisor CLI's and launcher/launch.py's SIGTERM/SIGINT
        handlers invoke (one copy, so it cannot diverge). Returns the
        conventional 128+signum exit code (or 1)."""
        self._teardown(list(self.procs.values()))
        self._clear_heartbeats()
        self._close_logs()
        return 128 + signum if signum else 1

    def install_signal_handlers(self):
        """SIGTERM/SIGINT → emergency_teardown + exit. Call from the
        process that OWNS this supervisor (the CLI, a supervising
        launcher) — not from library/test embedders, which keep their
        own handlers."""
        def _forward(signum, _frame):
            logger.warning(f"[supervisor] signal {signum}: tearing "
                           f"the world down")
            sys.exit(self.emergency_teardown(signum))

        signal.signal(signal.SIGTERM, _forward)
        signal.signal(signal.SIGINT, _forward)

    # --------------------------------------------------------------- run

    def run(self, deadline_s=None):
        """Supervise until the world exits clean (returns 0) or the
        restart budget is spent (returns ``EXIT_CRASH_LOOP``).
        ``deadline_s`` bounds the whole supervision wall clock — on
        expiry everything is torn down and TimeoutError raises (a
        harness guard; production runs leave it None)."""
        t_start = time.time()
        self._spawn(self.world)
        try:
            while True:
                time.sleep(self.poll_s)
                if deadline_s is not None \
                        and time.time() - t_start > deadline_s:
                    raise TimeoutError(
                        f"supervision exceeded {deadline_s}s "
                        f"(epoch {self.restart_epoch})")
                rcs = {r: p.poll() for r, p in self.procs.items()}
                dead = [(r, rc) for r, rc in rcs.items()
                        if rc is not None and rc != 0]
                if not dead:
                    if all(rc == 0 for rc in rcs.values()):
                        self._clear_heartbeats()
                        logger.info(
                            f"[supervisor] world exited clean after "
                            f"{self.restarts} restart(s)")
                        return 0
                    live = [r for r, rc in rcs.items() if rc is None]
                    stale = self._stale_ranks(live)
                    if not stale:
                        continue
                    dead = [(r, None) for r, _age in stale]
                    reasons = {r: f"heartbeat_stale:{age:.1f}s"
                               for r, age in stale}
                else:
                    reasons = {r: self._classify(rc) for r, rc in dead}
                code = self._incident(dead, reasons)
                if code is not None:
                    return code
        finally:
            # whatever path exits, never leave orphans or stale state
            self._teardown(list(self.procs.values()))
            self._clear_heartbeats()
            self._close_logs()

    def _incident(self, dead, reasons):
        """One rank-death/hang/freeze incident: record, tear down,
        shrink, back off, respawn — or, past the budget, latch the
        ``crash_loop`` dump and return the terminal exit code."""
        detect_ts = time.time()
        epoch_roles = self.roles_for_world(len(self.procs))
        for rank, rc in dead:
            role = epoch_roles.get(rank) if epoch_roles else None
            self.recorder.record(
                "rank_exit", rank=rank, exit_code=rc,
                reason=reasons[rank], restart_epoch=self.restart_epoch,
                world=len(self.procs), role=role)
            logger.warning(f"[supervisor] rank {rank} down "
                           f"({reasons[rank]}"
                           f"{', role ' + role if role else ''}), "
                           f"epoch {self.restart_epoch}")
        # casualties: ranks genuinely lost. A rank exiting EXIT_HANG is
        # a healthy DETECTOR reporting a stuck peer — if only detectors
        # exited, exactly the undetected peer(s) are the loss, floor 1.
        casualties = [r for r, _ in dead
                      if not reasons[r].startswith("hang_detected")]
        n_lost = len(casualties) if casualties else 1
        self.registry.counter("fault/rank_deaths").inc(n_lost)
        first = casualties[0] if casualties else dead[0][0]
        if self.watchdog is not None:
            self.watchdog.note_rank_dead(
                rank=first, reason=reasons[first],
                exit_code=dict(dead).get(first),
                restart_epoch=self.restart_epoch,
                world=len(self.procs))
        survivors = [p for r, p in self.procs.items() if p.poll() is None]
        self._teardown(list(self.procs.values()))
        self.recorder.record(
            "world_down", restart_epoch=self.restart_epoch,
            survivors_torn_down=len(survivors), lost=n_lost)
        self._clear_heartbeats()
        self._close_logs()
        world_now = len(self.procs)
        incident = {"epoch": self.restart_epoch, "dead": dict(dead),
                    "reasons": dict(reasons), "lost": n_lost,
                    "detect_ts": detect_ts, "world": world_now,
                    "roles": {r: epoch_roles.get(r) for r, _ in dead}
                    if epoch_roles else None}
        self.incidents.append(incident)

        next_world = solve_next_world(
            world_now, n_lost, valid_worlds=self.valid_worlds,
            min_world=self.min_world)
        if self.restarts >= self.max_restarts or next_world is None:
            why = "no_feasible_world" if next_world is None \
                else reasons[dead[0][0]]
            self.recorder.record(
                "crash_loop", restarts=self.restarts,
                max_restarts=self.max_restarts, world=world_now,
                last_reason=why)
            if self.watchdog is not None:
                self.watchdog.note_crash_loop(
                    restarts=self.restarts,
                    max_restarts=self.max_restarts, world=world_now,
                    last_reason=why)
            logger.error(
                f"[supervisor] crash loop: {self.restarts} restart(s) "
                f"spent (max {self.max_restarts}), last incident "
                f"{why}; giving up")
            return EXIT_CRASH_LOOP

        backoff = jittered_backoff(self.backoff_base_s, self.restarts,
                                   cap_s=self.backoff_max_s,
                                   rng=self._rng.random)
        self.restarts += 1
        self.restart_epoch += 1
        self.recorder.record(
            "restart", restart_epoch=self.restart_epoch,
            world_from=world_now, world_to=next_world,
            backoff_s=backoff, restarts=self.restarts,
            reason=reasons[dead[0][0]])
        self.registry.counter("fault/restarts").inc()
        self.registry.histogram("fault/backoff_s").observe(backoff)
        logger.warning(
            f"[supervisor] restarting: world {world_now} -> "
            f"{next_world}, epoch {self.restart_epoch}, backoff "
            f"{backoff:.2f}s ({self.restarts}/{self.max_restarts})")
        time.sleep(backoff)
        if self.watchdog is not None:
            self.watchdog.note_world_ok()   # next incident = new episode
        self.world = next_world
        self._spawn(next_world)
        return None


def main(argv=None):
    import argparse
    import json
    ap = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.runtime.elastic.supervisor",
        description="fault-tolerant multi-process training supervisor "
        "(ISSUE 15): spawn a local world over the DSTPU env contract, "
        "restart it shrunk-and-resumed on rank death/hang, bounded by "
        "--max_restarts")
    ap.add_argument("--world", type=int, default=1)
    ap.add_argument("--min_world", type=int, default=1)
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--hang_deadline", type=float, default=300.0)
    ap.add_argument("--heartbeat_dir", type=str, required=True)
    ap.add_argument("--dump_dir", type=str, default="")
    ap.add_argument("--grace_kill", type=float, default=5.0)
    ap.add_argument("--backoff_base", type=float, default=0.5)
    ap.add_argument("--backoff_max", type=float, default=30.0)
    ap.add_argument("--local_devices", type=int, default=0,
                    help="devices each process owns: on the CPU "
                    "harness it also sets the per-process virtual "
                    "device count; on a real host pass the chips per "
                    "worker so the elasticity shrink ladder counts "
                    "CHIPS, not processes (unset + --ds_config → "
                    "unconstrained arithmetic shrink, with a warning)")
    ap.add_argument("--ds_config", type=str, default="",
                    help="ds-config JSON: its elasticity block "
                    "constrains the shrink ladder, its fault_tolerance "
                    "block supplies rendezvous-retry knobs for workers")
    ap.add_argument("training_script", type=str)
    ap.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    valid = None
    rdv_retries = rdv_backoff = None
    if args.ds_config:
        with open(args.ds_config) as fh:
            pd = json.load(fh)
        if args.local_devices:
            valid = valid_worlds_from_elasticity(
                pd, local_devices=args.local_devices)
        else:
            # the ladder counts CHIPS; without the per-process chip
            # count a process-world ladder would be wrong on any
            # multi-chip host (world 6 × 4 chips = 24 is not on a
            # {1,2,3,4,6,8,12} ladder) — shrink arithmetically and let
            # the engines' own elasticity solve reject infeasible
            # worlds loudly
            logger.warning(
                "--ds_config given without --local_devices: cannot "
                "derive the chip-valid shrink ladder (unknown chips "
                "per process); restarts shrink arithmetically")
        ft = pd.get("fault_tolerance") or {}
        rdv_retries = ft.get("rendezvous_retries")
        rdv_backoff = ft.get("rendezvous_backoff_s")

    sup = Supervisor(
        [sys.executable, "-u", args.training_script]
        + args.training_script_args,
        args.world, min_world=args.min_world, valid_worlds=valid,
        heartbeat_dir=args.heartbeat_dir,
        dump_dir=args.dump_dir or None,
        hang_deadline_s=args.hang_deadline,
        grace_kill_s=args.grace_kill,
        max_restarts=args.max_restarts,
        backoff_base_s=args.backoff_base,
        backoff_max_s=args.backoff_max,
        local_devices=args.local_devices or None,
        rendezvous_retries=rdv_retries,
        rendezvous_backoff_s=rdv_backoff)

    sup.install_signal_handlers()
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
