"""Preemption handling (ISSUE 7): a signal hook + grace budget.

Shared TPU pools reclaim chips with a SIGTERM and a short grace window.
The :class:`PreemptionHandler` turns that into a flag the engine polls
at step boundaries (the only place a final snapshot is consistent):

- the signal handler itself does the minimum legal work — plain
  attribute stores stamping the arrival time and flag — because it can
  interrupt arbitrary Python INCLUDING code holding locks; the
  ``preempt_signal`` ring event is deferred to ``poll_event()`` at the
  next step boundary (taking the recorder lock inside signal context
  could deadlock);
- ``engine.train_batch`` checks ``requested`` at the end of the step
  and runs the FINAL snapshot through the async snapshotter, but only
  while ``remaining()`` grace budget is positive: a snapshot that
  cannot finish inside the grace window is aborted rather than half
  committed (the previous snapshot stays the valid one — the manifest
  is the commit point);
- the watchdog records the incident: one ``preempt`` flight-recorder
  dump per preemption, carrying the ring history leading up to it.

Handlers chain: the previously installed handler (a launcher's own
SIGTERM logic) still runs after ours. ``restore()`` reinstalls the
original handlers — tests and short-lived engines should call it.
"""

import signal
import time
import weakref

from deepspeed_tpu.utils.logging import logger


class PreemptionHandler:
    def __init__(self, signals=("SIGTERM",), grace_s=30.0, recorder=None):
        self.grace_s = float(grace_s)  # sync-ok: host config scalar
        self._recorder = recorder
        self._requested = None       # (ts, signal name)
        self._event_pending = False  # preempt_signal event not yet recorded
        self._installed = {}         # signum -> previous handler
        try:
            for name in signals or ():
                signum = getattr(signal, str(name), None)
                if not isinstance(signum, signal.Signals):
                    raise ValueError(f"unknown signal {name!r}")
                try:
                    prev = signal.getsignal(signum)
                    signal.signal(signum,
                                  self._make_handler(str(name), prev))
                    self._installed[signum] = prev
                except ValueError:
                    # not the main thread: signal delivery cannot be
                    # hooked here — programmatic request() still works
                    logger.warning(
                        f"PreemptionHandler: cannot install {name} "
                        f"handler off the main thread; request() "
                        f"remains available")
        except Exception:
            self.restore()   # no half-installed handler set may leak
            raise

    def _make_handler(self, name, prev):
        # the closure holds only a WEAKREF to this handler object: the
        # signal table pins installed closures for the process lifetime,
        # and a strong ref would pin every engine (and its captured
        # recorder) ever constructed — a dead handler becomes a
        # pass-through to the chained previous handler instead
        ref = weakref.ref(self)

        def _handler(signum, frame):
            # ASYNC-SIGNAL-SAFE by construction: the handler runs on the
            # main thread between bytecodes and may interrupt code that
            # HOLDS locks (the flight recorder's ring lock is taken many
            # times per step) — acquiring any non-reentrant lock here
            # can deadlock the process past its grace window. So the
            # handler only does plain attribute stores; the recorder
            # event is deferred to poll_event() at the step boundary.
            live = ref()
            if live is not None:
                live.request(name)
            if callable(prev):
                prev(signum, frame)
        return _handler

    def _rec(self):
        if self._recorder is None:
            from deepspeed_tpu.telemetry import default_recorder
            self._recorder = default_recorder()
        return self._recorder

    def request(self, source="manual"):
        """Mark preemption requested (signal handler or programmatic
        harness). Idempotent — the first request starts the grace
        clock. Lock-free plain stores only: this runs inside signal
        context (see _make_handler)."""
        if self._requested is None:
            self._requested = (time.monotonic(), str(source))
            self._event_pending = True

    def poll_event(self):
        """Record the deferred ``preempt_signal`` event — called by the
        engine at the step boundary, OUTSIDE signal context, where
        taking the recorder lock is safe."""
        if self._event_pending:
            self._event_pending = False
            self._rec().record("preempt_signal", signal=self.source,
                               grace_s=self.grace_s)

    @property
    def requested(self):
        return self._requested is not None

    def remaining(self):
        """Grace seconds left (None when no preemption is pending)."""
        if self._requested is None:
            return None
        return self.grace_s - (time.monotonic() - self._requested[0])

    @property
    def source(self):
        return self._requested[1] if self._requested else None

    def restart_clock(self):
        """Restart the grace clock at NOW, keeping the request (the
        multi-process agreement point: signals arrive at arbitrary
        times but the final snapshot only starts at an aligned interval
        boundary, so the budget for the snapshot WORK counts from the
        boundary — size ``interval_steps × step_time`` against the
        scheduler's external kill deadline accordingly)."""
        if self._requested is not None:
            self._requested = (time.monotonic(), self._requested[1])

    def reset(self):
        self._requested = None
        self._event_pending = False

    def restore(self):
        """Reinstall the handlers that were active before this one."""
        for signum, prev in self._installed.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):
                pass
        self._installed = {}
