"""Deterministic fault injection — the harness the elastic tests drive
end-to-end (ISSUE 7). Production code calls ``fire(point)`` at named
injection points; with nothing registered that is one dict lookup.
Tests register callables with ``inject(point, fn)`` to simulate the
failure at exactly that point:

- ``step_end`` (engine.train_batch, after the optimizer step + park) —
  kill-at-step lands here via :func:`kill_at_step`;
- ``snapshot_between_renames`` (snapshot commit, after the old tag was
  moved aside and before the staging dir takes its place) — the
  crash-between-renames window;
- ``ckpt_between_renames`` (runtime/checkpointing.py save commit) —
  the same window in the blocking checkpoint path (the hazard the
  comment at checkpointing.py:318 documents);
- serving fire points (ISSUE 11): ``serving_admit`` (pages allocated,
  prefill not yet dispatched — the mid-prefill crash window),
  ``serving_spec_verify`` (the verify dispatch ran, nothing committed
  — the mid-spec-verify window), ``serving_tick_end`` (the scheduler's
  step boundary, where :func:`kill_at_serving_tick` delivers a real
  SIGTERM mid-serve), ``serving_handoff`` (ISSUE 14: the request
  is extracted from its prefill engine but not yet delivered to a
  decode engine — the page transport dying with the bytes in flight,
  via :func:`crash_during_handoff`), and ``serving_deliver`` (ISSUE
  15: the decode engine has ADMITTED the packet's pages but the
  scatter/adoption never ran — the delivery-side crash whose unwind
  path must decref the just-admitted pages instead of leaking them,
  via :func:`crash_during_delivery`);
- ``collective_enter`` (engine.train_batch, immediately before the
  step dispatch that executes the cross-process collectives — ISSUE
  15): :func:`hang_in_collective` parks one rank here so its PEERS
  block inside the boundary exchange, the exact eternal-hang shape a
  SIGKILLed/hung rank inflicts on its survivors. The sleeping rank's
  heartbeat thread keeps beating (daemon threads survive a main-thread
  sleep), so only the survivors' blocked-in-dispatch watchdog can see
  this — which is the point.

Post-commit corruptions (a torn manifest, a rotted shard) are plain
file edits — :func:`tear_manifest` / :func:`rot_shard` — because they
model damage that happens AFTER the writer finished (a lost page, a
bad sector), not a crash inside it.

Stdlib-only on purpose: runtime/checkpointing.py and the engine fire
points from inside their commit paths, and this module must never pull
jax (or a sibling elastic module) into those import graphs.
"""

import contextlib
import os
import signal
import time

_HOOKS = {}   # point name -> list of callables


class SimulatedCrash(RuntimeError):
    """Raised by an injected fault to model a process dying at the
    injection point (the caller's stack unwinds exactly like a crash
    would leave the filesystem)."""


def fire(point, **kw):
    """Invoke the callables registered at ``point`` (no-op when none)."""
    for fn in _HOOKS.get(point, ()):
        fn(**kw)


@contextlib.contextmanager
def inject(point, fn):
    """Register ``fn`` at ``point`` for the duration of the block."""
    _HOOKS.setdefault(point, []).append(fn)
    try:
        yield
    finally:
        _HOOKS[point].remove(fn)
        if not _HOOKS[point]:
            del _HOOKS[point]


def clear():
    _HOOKS.clear()


# ---------------------------------------------------------------- scenarios

def kill_at_step(at_step, sig=signal.SIGTERM):
    """Context manager: deliver ``sig`` to this process the first time
    the engine finishes training step ``at_step`` — the deterministic
    stand-in for a scheduler preempting the job mid-run. The signal
    goes through the real kernel delivery path, so the
    PreemptionHandler under test sees exactly what production would."""
    fired = []

    def _fn(step=None, **_kw):
        if step == at_step and not fired:
            fired.append(True)
            os.kill(os.getpid(), sig)

    return inject("step_end", _fn)


def sigkill_at_step(at_step):
    """Context manager: SIGKILL this process the first time the engine
    finishes step ``at_step`` (ISSUE 15) — the hard-death scenario
    (OOM killer, node loss, ``kill -9``): no handler runs, no final
    snapshot, no goodbye. Survivor ranks block forever inside their
    next collective unless the hang watchdog (runtime/elastic/hang.py)
    converts the stall into an exit; the launcher-level supervisor
    (runtime/elastic/supervisor.py) sees the death and restarts the
    shrunk world."""
    return kill_at_step(at_step, sig=signal.SIGKILL)


def exit_at_step(at_step, code=1):
    """Context manager: hard ``os._exit(code)`` at step ``at_step`` —
    the deterministic crash-LOOP ingredient (every restarted epoch dies
    the same way until the supervisor's ``max_restarts`` bound trips).
    ``os._exit`` skips atexit/finally exactly like a crash would."""
    def _fn(step=None, **_kw):
        if step == at_step:
            os._exit(code)

    return inject("step_end", _fn)


def hang_in_collective(at_step, hang_s=3600.0):
    """Context manager: park this rank for ``hang_s`` seconds at the
    ``collective_enter`` point of step ``at_step`` — it never dispatches
    the step, so every PEER rank blocks inside the boundary collective
    (the in-collective hang, ISSUE 15). The peers' hang watchdog must
    detect the stall within ``fault_tolerance.hang_deadline_s`` and
    exit with the distinct hang code instead of hanging forever."""
    fired = []

    def _fn(step=None, **_kw):
        if step == at_step and not fired:
            fired.append(True)
            time.sleep(hang_s)

    return inject("collective_enter", _fn)


def kill_at_serving_tick(at_tick, sig=signal.SIGTERM):
    """Context manager: deliver ``sig`` to this process the first time
    the serving scheduler finishes tick ``at_tick`` — SIGTERM
    mid-serve, through the real kernel delivery path (the serving
    drain-or-snapshot sibling of :func:`kill_at_step`). With a drafter
    attached the tick boundary sits BETWEEN speculative rounds, so the
    snapshot the handler triggers must contain only verified tokens."""
    fired = []

    def _fn(tick=None, **_kw):
        if tick is not None and tick >= at_tick and not fired:
            fired.append(True)
            os.kill(os.getpid(), sig)

    return inject("serving_tick_end", _fn)


def crash_replica_mid_prefill(match_rid=None, times=1):
    """Context manager: crash at ``serving_admit`` — the request's
    pages are allocated but its prefill never dispatched (the replica
    dies mid-admission; pool recovery must re-serve it from scratch).
    ``match_rid`` restricts the crash to one request id; ``times``
    bounds how many matching admissions crash (``None`` = every one —
    the permanently-poisoned-request scenario the bounded-retry test
    drives)."""
    fired = [0]

    def _fn(rid=None, **_kw):
        if match_rid is not None and rid != match_rid:
            return
        if times is not None and fired[0] >= times:
            return
        fired[0] += 1
        raise SimulatedCrash(
            f"injected crash at serving_admit (rid={rid})")

    return inject("serving_admit", _fn)


def crash_during_handoff(match_rid=None, times=1):
    """Context manager: crash at ``serving_handoff`` — the request was
    EXTRACTED from its prefill-role engine (pages decreffed, gathered
    bytes only in the in-flight packet) but never delivered to a
    decode engine: the transport died with the bytes. The router must
    replay the request from its wire doc (ISSUE 14). Same knobs as
    :func:`crash_replica_mid_prefill`."""
    fired = [0]

    def _fn(rid=None, **_kw):
        if match_rid is not None and rid != match_rid:
            return
        if times is not None and fired[0] >= times:
            return
        fired[0] += 1
        raise SimulatedCrash(
            f"injected crash at serving_handoff (rid={rid})")

    return inject("serving_handoff", _fn)


def crash_during_delivery(match_rid=None, times=1):
    """Context manager: crash at ``serving_deliver`` — the decode
    engine already ADMITTED the packet's pages (allocated/increffed
    through the refcounted allocator) but the KV scatter and slot
    adoption never happened (ISSUE 15 satellite, the delivery-side
    crash PR 14's review flagged). ``deliver_handoff`` must unwind the
    admission — decref the just-admitted pages — and the router
    replays the request from its wire doc; the leak-fence test pins
    that the pool drains back to full. Same knobs as
    :func:`crash_during_handoff`."""
    fired = [0]

    def _fn(rid=None, **_kw):
        if match_rid is not None and rid != match_rid:
            return
        if times is not None and fired[0] >= times:
            return
        fired[0] += 1
        raise SimulatedCrash(
            f"injected crash at serving_deliver (rid={rid})")

    return inject("serving_deliver", _fn)


def crash_replica_mid_spec_verify(at_round=1):
    """Context manager: crash at the ``at_round``-th
    ``serving_spec_verify`` point — the verify dispatch completed but
    no token of the round was committed (drafted-but-unverified rows
    sit past every slot's pos and must never surface in a restore)."""
    seen = [0]

    def _fn(**_kw):
        seen[0] += 1
        if seen[0] == at_round:
            raise SimulatedCrash(
                f"injected crash at serving_spec_verify round {at_round}")

    return inject("serving_spec_verify", _fn)


def crash_between_renames(point="snapshot_between_renames"):
    """Context manager: crash the commit between its two renames —
    the window where the old tag is already moved aside but the new
    save has not taken its place."""

    def _fn(**_kw):
        raise SimulatedCrash(f"injected crash at {point}")

    return inject(point, _fn)


def tear_manifest(snap_dir, keep_bytes=20):
    """Truncate a committed snapshot's manifest mid-JSON (a torn
    write): loaders must treat the snapshot as invalid and fall back."""
    path = os.path.join(snap_dir, "manifest.json")
    with open(path, "r+b") as fh:
        fh.truncate(keep_bytes)
    return path


def rot_shard(snap_dir, nbytes=8):
    """Flip the leading bytes of the first data shard of a committed
    snapshot (bit rot / bad sector): the manifest checksum must catch
    it at load."""
    names = sorted(n for n in os.listdir(snap_dir) if n.endswith(".bin"))
    assert names, f"no data shards in {snap_dir}"
    path = os.path.join(snap_dir, names[0])
    with open(path, "r+b") as fh:
        orig = fh.read(nbytes)
        fh.seek(0)
        fh.write(bytes(b ^ 0xFF for b in orig))
    return path
