"""PipelineEngine — rebuild of deepspeed/runtime/pipe/engine.py:102's role.

Executes a PipelineModule under the instruction schedules in schedule.py.
Single-stage (pipe axis = 1) runs the module sequentially through the base
engine — the degenerate DataParallelSchedule case. Multi-stage execution
lowers the TrainSchedule to the 1F1B SPMD pipeline in
deepspeed_tpu/parallel/pipeline_1f1b.py (stage-stacked params sharded over
the 'pipe' mesh axis, microbatches rotated with ppermute, backward replay
of the even/odd schedule) rather than the reference's per-rank NCCL p2p
interpreter (pipe/engine.py:1209).
"""

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe import schedule as pipe_schedule
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.utils.logging import logger


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, *args, model=None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a PipelineModule"
        # the base engine lowers the module right after it resolves the
        # final mesh (kwarg or config section, after distributed init) and
        # before any param/state initialization — see engine.py mesh setup
        super().__init__(*args, model=model, **kwargs)
        self.num_stages = model.num_stages
        # ZeRO-2/3 + PP restriction, same as reference pipe/engine.py:55
        assert self.zero_optimization_stage() < 2, \
            "ZeRO-2 and ZeRO-3 are incompatible with pipeline parallelism"
        # module loss_fn wins if the engine got none (reference uses
        # PipelineModule.loss_fn for the last stage)
        if self._loss_fn_user is None and model.loss_fn is not None:
            mod = self.module
            client_loss = model.loss_fn

            def pipeline_loss(params, batch, rng, keep_prob):
                if isinstance(batch, (tuple, list)) and len(batch) == 2:
                    x, y = batch
                else:
                    x, y = batch, batch
                out = mod.apply({"params": params}, x)
                return client_loss(out, y)
            self._loss_fn_user = pipeline_loss

    def train_schedule(self):
        return pipe_schedule.TrainSchedule(
            micro_batches=self.gradient_accumulation_steps(),
            stages=self.num_stages,
            stage_id=0)

    def train_batch(self, batch=None, data_iter=None):
        """reference pipe/engine.py:250 — consumes gas micro-batches.
        Multi-stage lowering happens inside the jitted step (the base
        engine's scan *is* the pipeline loop once stage params are sharded
        over the pipe axis)."""
        return super().train_batch(batch=batch, data_iter=data_iter)

    def eval_batch(self, batch):
        return super().eval_batch(batch)

    def is_first_stage(self):
        return True  # SPMD: every process holds the whole pipeline program

    def is_last_stage(self):
        return True

    def set_dataiterator(self, iterator):
        self._data_iterator = iterator
