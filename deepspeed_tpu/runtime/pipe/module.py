"""Pipeline module — rebuild of deepspeed/runtime/pipe/module.py:25,73,87.

`LayerSpec` delays layer construction so each stage only materializes its own
layers (the reference's motivation, module.py:25). `PipelineModule` expresses
a sequential model as specs, partitions them into stages
(uniform / parameters / type:regex — module.py:355-410), and exposes
`init`/`apply` so it drops into the engine like any flax model.

TPU mapping: stage s's layers live on the mesh's 'pipe' axis coordinate s;
the PipelineEngine runs the 1F1B schedule with ppermute transfers between
stage sub-meshes (pipe/engine.py here). With pipe=1 the module is just a
sequential container (and still exercises partitioning logic for tests).
"""

import re
from typing import Any, Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Builds-on-demand layer description (reference module.py:25)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec only supports classes")

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared with every other layer of the same
    ``key`` (reference module.py:73 — embedding/unembedding tying). The
    forward_fn selects how the shared module is applied at this position."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items, num_parts):
    """Even split boundaries: len == num_parts+1 (reference
    runtime/utils.py partition_uniform)."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items - chunk * num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunk + (1 if p <= residual else 0)
    return parts


def partition_balanced(weights, num_parts, eps=1e-3):
    """Boundaries minimizing the max part weight — binary search over
    capacity + greedy packing (reference runtime/utils.py
    partition_balanced semantics)."""
    weights = [float(w) for w in weights]
    n = len(weights)
    if num_parts >= n:
        return partition_uniform(n, num_parts)

    def feasible(cap):
        parts, load, used = [0], 0.0, 1
        for i, w in enumerate(weights):
            if load + w > cap and load > 0:
                used += 1
                parts.append(i)
                load = 0.0
                if used > num_parts:
                    return None
            load += w
        parts.append(n)
        while len(parts) < num_parts + 1:
            parts.insert(-1, parts[-1])
        return parts

    lo, hi = max(weights), sum(weights)
    best = feasible(hi)
    while hi - lo > eps * max(sum(weights), 1.0):
        mid = (lo + hi) / 2
        cand = feasible(mid)
        if cand is not None:
            best, hi = cand, mid
        else:
            lo = mid
    return best


class PipelineModule:
    """See module docstring. Key ctor args mirror the reference
    (module.py:87): layers, num_stages, topology, loss_fn, seed_layers,
    partition_method, activation_checkpoint_interval."""

    def __init__(self,
                 layers,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False,
                 base_seed: int = 1234,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 checkpointable_layers=None,
                 num_microbatches: Optional[int] = None):
        self._layer_specs = list(layers)
        self._num_layers = len(self._layer_specs)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.checkpointable_layers = checkpointable_layers
        self.num_microbatches = num_microbatches
        self._spmd_mesh = None        # set by lower_to_spmd
        self._trunk = None            # (start, stop) homogeneous layer run
        self._trunk_refined = False   # shape-refinement pinned (in _stack_trunk)
        self._warned_sequential_layout = False

        if num_stages is None and topology is None:
            num_stages = 1
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = num_stages
        self.topology = topology

        # build every layer (single-program SPMD: all stages traced together,
        # GSPMD places each stage's params on its pipe coordinate)
        self.forward_funcs: List[Any] = []
        self.tied_modules = {}
        self.tied_specs = {}
        for i, spec in enumerate(self._layer_specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in self.tied_modules:
                    self.tied_modules[spec.key] = spec.build()
                    self.tied_specs[spec.key] = spec
                self.forward_funcs.append((spec.key, spec.forward_fn))
            elif isinstance(spec, LayerSpec):
                self.forward_funcs.append(spec.build())
            elif callable(spec):
                self.forward_funcs.append(spec)
            else:
                raise TypeError(f"Layer specification {spec} is not supported")

        self.parts = None  # stage boundaries; set by _partition_layers
        self._partition_layers_static()

    # -- partitioning ------------------------------------------------------
    def _layer_weights_by_class(self, regex):
        pattern = re.compile(regex)
        weights = []
        for f in self.forward_funcs:
            cls = type(f[0] if isinstance(f, tuple) else f).__name__
            weights.append(1.0 if pattern.search(cls) else 0.0)
        return weights

    def _partition_layers_static(self):
        """Partition without parameter counts (uniform / type:regex). The
        'parameters' method refines boundaries at init() when shapes are
        known (the reference counts torch params eagerly, module.py:388)."""
        method = (self.partition_method or "uniform").lower()
        if method.startswith("type:"):
            weights = self._layer_weights_by_class(method[5:])
            if sum(weights) == 0:
                weights = [1.0] * self._num_layers
            self.parts = partition_balanced(weights, self.num_stages)
        else:
            self.parts = partition_uniform(self._num_layers, self.num_stages)

    def _partition_layers_by_params(self, params):
        counts = []
        for i in range(self._num_layers):
            sub = params.get(f"layer_{i}", {})
            counts.append(sum(int(np.prod(p.shape))
                              for p in jax.tree_util.tree_leaves(sub)) + 1.0)
        self.parts = partition_balanced(counts, self.num_stages)
        for s in range(self.num_stages):
            logger.info(f"pipeline stage {s}: layers "
                        f"[{self.parts[s]}, {self.parts[s+1]}) "
                        f"params={sum(counts[self.parts[s]:self.parts[s+1]])/1e6:.2f}M")

    # -- SPMD lowering -----------------------------------------------------
    def _find_homogeneous_trunk(self):
        """Longest contiguous run of pairwise-identical LayerSpecs (same
        class, args, kwargs; not tied, flax modules). These are the layers
        that can be stage-stacked for the 1F1B SPMD executor; layers before/
        after the run ("prefix"/"suffix" — embeddings, heads, norms) run on
        every stage, replicated w.r.t. the pipe axis."""
        def key(i):
            spec = self._layer_specs[i]
            if isinstance(spec, TiedLayerSpec) or \
                    not isinstance(spec, LayerSpec):
                return None
            f = self.forward_funcs[i]
            if not (hasattr(f, "init") and hasattr(f, "apply")):
                return None
            try:
                return (spec.typename, repr(spec.module_args),
                        repr(sorted(spec.module_kwargs.items())))
            except Exception:
                return None

        best, cur_start = (0, 0), 0
        prev = object()
        for i in range(self._num_layers + 1):
            k = key(i) if i < self._num_layers else None
            if k is None or k != prev:
                cur_start = i
            prev = k
            if k is not None and i + 1 - cur_start > best[1] - best[0]:
                best = (cur_start, i + 1)
        return best

    def lower_to_spmd(self, mesh, num_microbatches: Optional[int] = None):
        """Configure pipelined SPMD execution over ``mesh``'s 'pipe' axis:
        the homogeneous trunk is stage-stacked and run by the 1F1B executor
        (parallel/pipeline_1f1b.py); called by PipelineEngine when the mesh
        has pipe > 1. Raises if the model has no trunk that divides into
        the pipe stages (the reference would equally fail to balance such
        a model across stages, module.py:355)."""
        from deepspeed_tpu.parallel import mesh as mesh_lib
        S = mesh_lib.mesh_axis_size(mesh, mesh_lib.PIPE_AXIS)
        start, stop = self._find_homogeneous_trunk()
        run = stop - start
        if run < S:
            raise ValueError(
                f"PipelineModule: longest homogeneous layer run is {run} "
                f"(layers [{start}, {stop})) but the mesh has {S} pipeline "
                f"stages; need at least one layer per stage. Express the "
                f"repeated block as identical LayerSpecs to pipeline it.")
        # keep only a multiple of S so stages stack evenly; leftovers join
        # the suffix (run uniformly on all stages)
        stop = start + (run // S) * S
        self._trunk = (start, stop)
        self._trunk_refined = False   # fresh lowering invalidates refinement
        self._spmd_mesh = mesh
        if self.num_stages != S:
            if self.num_stages not in (None, 1):
                logger.warning(
                    f"PipelineModule num_stages={self.num_stages} != mesh "
                    f"pipe axis {S}; using mesh value")
            self.num_stages = S
            # keep reporting surfaces (parts/stage_of_layer) consistent
            # with the new stage count
            self._partition_layers_static()
        if num_microbatches is not None:
            self.num_microbatches = num_microbatches
        if self.num_microbatches is None:
            self.num_microbatches = S
        logger.info(
            f"PipelineModule lowered to SPMD: trunk layers "
            f"[{start}, {stop}) over {S} stages "
            f"({(stop - start) // S}/stage), prefix {start}, "
            f"suffix {self._num_layers - stop}, "
            f"micro_batches={self.num_microbatches}")
        return self

    def _refine_trunk_by_shapes(self, params):
        """Spec equality can't see data-dependent shapes (the first Dense
        of a width-W run has an input-width kernel); shrink the trunk to
        the longest sub-run whose param trees match exactly, then floor to
        a stage multiple. Pure: returns (start, stop) without touching
        self — freezing happens once in _stack_trunk."""
        start, stop = self._trunk
        S = self.num_stages

        def sig(i):
            leaves, treedef = jax.tree_util.tree_flatten(
                params[f"layer_{i}"])
            return (treedef, tuple((x.shape, x.dtype) for x in leaves))

        best = (start, start)
        run_start = start
        for i in range(start, stop + 1):
            if i == stop or (i > run_start and sig(i) != sig(run_start)):
                if i - run_start > best[1] - best[0]:
                    best = (run_start, i)
                run_start = i
        start, stop = best
        stop = start + ((stop - start) // S) * S
        if stop - start < S:
            raise ValueError(
                f"PipelineModule: after shape matching, the homogeneous "
                f"trunk is {best[1] - best[0]} layers — fewer than the "
                f"{S} pipeline stages. Express the repeated block as "
                f"shape-identical LayerSpecs to pipeline it.")
        return start, stop

    def _stack_trunk(self, params, freeze=True, bounds=None):
        """Per-layer params → stage-stacked trunk + the rest untouched.

        ``freeze=True`` (init/lowering time) pins the shape-refined trunk
        bounds on the module; apply-time conversions pass freeze=False with
        precomputed ``bounds`` so tracing stays side-effect-free and
        mixed-layout callers can't move the trunk between calls."""
        from deepspeed_tpu.parallel.pipeline_1f1b import stack_stage_params
        if bounds is not None:
            start, stop = bounds
        elif self._trunk_refined:
            start, stop = self._trunk
        else:
            start, stop = self._refine_trunk_by_shapes(params)
            if freeze:
                self._trunk = (start, stop)
                self._trunk_refined = True
        layer_trees = [params[f"layer_{i}"] for i in range(start, stop)]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *layer_trees)
        trunk_keys = {f"layer_{i}" for i in range(start, stop)}
        out = {k: v for k, v in params.items() if k not in trunk_keys}
        out["trunk_stages"] = stack_stage_params(stacked, self.num_stages)
        return out

    def unstack_trunk(self, params):
        """Inverse of _stack_trunk — for checkpoint interop with the
        sequential layout (state_dict_factory-style resharding)."""
        from deepspeed_tpu.parallel.pipeline_1f1b import unstack_stage_params
        start, stop = self._trunk
        flat = unstack_stage_params(params["trunk_stages"])
        out = {k: v for k, v in params.items() if k != "trunk_stages"}
        for i in range(start, stop):
            out[f"layer_{i}"] = jax.tree_util.tree_map(
                lambda x, i=i: x[i - start], flat)
        return out

    def param_partition_specs(self, params_shapes):
        """Base GSPMD specs: 'pipe' on the stage dim of trunk_stages,
        replicated elsewhere (consumed by the engine's ZeroPartitioner)."""
        from jax.sharding import PartitionSpec as P
        from deepspeed_tpu.parallel import mesh as mesh_lib

        def walk(tree, under_trunk):
            if isinstance(tree, dict):
                return {k: walk(v, under_trunk or k == "trunk_stages")
                        for k, v in tree.items()}
            if under_trunk:
                return P(mesh_lib.PIPE_AXIS)
            return P()
        tree = params_shapes.get("params", params_shapes) \
            if isinstance(params_shapes, dict) else params_shapes
        return walk(tree, False)

    def stage_of_layer(self, layer_idx):
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        return self.num_stages - 1

    def stage_layers(self, stage_id):
        return list(range(self.parts[stage_id], self.parts[stage_id + 1]))

    # -- flax-like interface ----------------------------------------------
    def _apply_layer(self, idx, layer_params, x, tied_params):
        f = self.forward_funcs[idx]
        if isinstance(f, tuple):  # tied layer
            key, forward_fn = f
            module = self.tied_modules[key]
            p = tied_params[key]
            if forward_fn is not None:
                return forward_fn(module, p, x)
            return module.apply({"params": p}, x)
        if hasattr(f, "apply") and hasattr(f, "init"):
            return f.apply({"params": layer_params}, x)
        return f(x)

    def init(self, rng, x):
        params = {}
        tied = {}
        h = x
        for i, f in enumerate(self.forward_funcs):
            if self.seed_layers:
                rng = jax.random.fold_in(jax.random.PRNGKey(self.base_seed), i)
            if isinstance(f, tuple):
                key, forward_fn = f
                module = self.tied_modules[key]
                if key not in tied:
                    rng, sub = jax.random.split(rng)
                    tied[key] = module.init(sub, h)["params"]
                h = self._apply_layer(i, None, h, tied)
            elif hasattr(f, "init"):
                rng, sub = jax.random.split(rng)
                variables = f.init(sub, h)
                params[f"layer_{i}"] = variables.get("params", variables)
                h = self._apply_layer(i, params[f"layer_{i}"], h, tied)
            else:
                h = f(h)
        params["tied"] = tied
        if (self.partition_method or "").lower() == "parameters":
            self._partition_layers_by_params(params)
        if self._spmd_mesh is not None:
            params = self._stack_trunk(params)
        return {"params": params}

    def apply(self, variables, x, inference=False, **kwargs):
        params = variables["params"]
        if self._spmd_mesh is not None:
            if "trunk_stages" not in params:
                # user-supplied params in the sequential layout: re-layout
                # (pure reshape/stack — safe under jit) instead of silently
                # running un-pipelined on a pipe>1 mesh
                if not self._warned_sequential_layout:
                    self._warned_sequential_layout = True
                    logger.warning(
                        "PipelineModule: converting sequential-layout params "
                        "to the stage-stacked layout for pipelined execution")
                # compute the shape-refined bounds once and hand the SAME
                # bounds to both the stacking and the prefix/suffix loops —
                # _apply_pipelined must not read stale self._trunk here
                trunk = self._trunk if self._trunk_refined \
                    else self._refine_trunk_by_shapes(params)
                params = self._stack_trunk(dict(params), freeze=False,
                                           bounds=trunk)
                return self._apply_pipelined(params, x, trunk=trunk,
                                             inference=inference)
            return self._apply_pipelined(params, x, inference=inference)
        tied = params.get("tied", {})
        h = x
        for i in range(self._num_layers):
            layer_params = params.get(f"layer_{i}")
            if self.activation_checkpoint_interval > 0 and \
                    i % self.activation_checkpoint_interval == 0:
                h = jax.checkpoint(
                    lambda p, hh, idx=i: self._apply_layer(idx, p, hh, tied)
                )(layer_params, h)
            else:
                h = self._apply_layer(i, layer_params, h, tied)
        return h

    def _apply_pipelined(self, params, x, trunk=None, inference=False):
        """Prefix layers (replicated w.r.t. pipe) → pipelined trunk →
        suffix. ``inference=True`` runs the forward-only InferenceSchedule
        program (no backward is built; for eval/serving)."""
        from deepspeed_tpu.parallel.pipeline_1f1b import (
            pipeline_1f1b, pipeline_infer)
        start, stop = trunk if trunk is not None else self._trunk
        tied = params.get("tied", {})
        trunk_module = self.forward_funcs[start]

        h = x
        for i in range(start):
            h = self._apply_layer(i, params.get(f"layer_{i}"), h, tied)

        M = self.num_microbatches
        B = h.shape[0]
        assert B % M == 0, (f"batch {B} not divisible by "
                            f"num_microbatches {M}")

        def stage_fn(stage_params, hh):
            def one_layer(carry, layer_params):
                def body(p, c):
                    return trunk_module.apply({"params": p}, c)
                if self.activation_checkpoint_interval > 0:
                    # honor the module's remat config inside the stage:
                    # bounds the vjp residuals of a multi-layer stage body
                    # to layer boundaries (same knob as the sequential path)
                    body = jax.checkpoint(body, prevent_cse=False)
                return body(layer_params, carry), None
            hh, _ = jax.lax.scan(one_layer, hh, stage_params)
            return hh

        mb = h.reshape((M, B // M) + h.shape[1:])
        run = pipeline_infer if inference else pipeline_1f1b
        h = run(stage_fn, params["trunk_stages"], mb, self._spmd_mesh)
        h = h.reshape((B,) + h.shape[2:])

        for i in range(stop, self._num_layers):
            h = self._apply_layer(i, params.get(f"layer_{i}"), h, tied)
        return h

    def __call__(self, x):
        raise RuntimeError("PipelineModule must be used through an engine")

    def num_layers(self):
        return self._num_layers

    def topology_grid(self):
        return self.topology
