"""Pipeline module — rebuild of deepspeed/runtime/pipe/module.py:25,73,87.

`LayerSpec` delays layer construction so each stage only materializes its own
layers (the reference's motivation, module.py:25). `PipelineModule` expresses
a sequential model as specs, partitions them into stages
(uniform / parameters / type:regex — module.py:355-410), and exposes
`init`/`apply` so it drops into the engine like any flax model.

TPU mapping: stage s's layers live on the mesh's 'pipe' axis coordinate s;
the PipelineEngine runs the 1F1B schedule with ppermute transfers between
stage sub-meshes (pipe/engine.py here). With pipe=1 the module is just a
sequential container (and still exercises partitioning logic for tests).
"""

import re
from typing import Any, Callable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Builds-on-demand layer description (reference module.py:25)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not issubclass(typename, object):
            raise RuntimeError("LayerSpec only supports classes")

    def build(self, log=False):
        if log:
            logger.info(f"building {repr(self)}")
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({self.typename.__name__})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared with every other layer of the same
    ``key`` (reference module.py:73 — embedding/unembedding tying). The
    forward_fn selects how the shared module is applied at this position."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 tied_weight_attr="embedding", **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items, num_parts):
    """Even split boundaries: len == num_parts+1 (reference
    runtime/utils.py partition_uniform)."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items - chunk * num_parts
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunk + (1 if p <= residual else 0)
    return parts


def partition_balanced(weights, num_parts, eps=1e-3):
    """Boundaries minimizing the max part weight — binary search over
    capacity + greedy packing (reference runtime/utils.py
    partition_balanced semantics)."""
    weights = [float(w) for w in weights]
    n = len(weights)
    if num_parts >= n:
        return partition_uniform(n, num_parts)

    def feasible(cap):
        parts, load, used = [0], 0.0, 1
        for i, w in enumerate(weights):
            if load + w > cap and load > 0:
                used += 1
                parts.append(i)
                load = 0.0
                if used > num_parts:
                    return None
            load += w
        parts.append(n)
        while len(parts) < num_parts + 1:
            parts.insert(-1, parts[-1])
        return parts

    lo, hi = max(weights), sum(weights)
    best = feasible(hi)
    while hi - lo > eps * max(sum(weights), 1.0):
        mid = (lo + hi) / 2
        cand = feasible(mid)
        if cand is not None:
            best, hi = cand, mid
        else:
            lo = mid
    return best


class PipelineModule:
    """See module docstring. Key ctor args mirror the reference
    (module.py:87): layers, num_stages, topology, loss_fn, seed_layers,
    partition_method, activation_checkpoint_interval."""

    def __init__(self,
                 layers,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 seed_layers: bool = False,
                 base_seed: int = 1234,
                 partition_method: str = "parameters",
                 activation_checkpoint_interval: int = 0,
                 checkpointable_layers=None):
        self._layer_specs = list(layers)
        self._num_layers = len(self._layer_specs)
        self.loss_fn = loss_fn
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.checkpointable_layers = checkpointable_layers

        if num_stages is None and topology is None:
            num_stages = 1
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = num_stages
        self.topology = topology

        # build every layer (single-program SPMD: all stages traced together,
        # GSPMD places each stage's params on its pipe coordinate)
        self.forward_funcs: List[Any] = []
        self.tied_modules = {}
        self.tied_specs = {}
        for i, spec in enumerate(self._layer_specs):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in self.tied_modules:
                    self.tied_modules[spec.key] = spec.build()
                    self.tied_specs[spec.key] = spec
                self.forward_funcs.append((spec.key, spec.forward_fn))
            elif isinstance(spec, LayerSpec):
                self.forward_funcs.append(spec.build())
            elif callable(spec):
                self.forward_funcs.append(spec)
            else:
                raise TypeError(f"Layer specification {spec} is not supported")

        self.parts = None  # stage boundaries; set by _partition_layers
        self._partition_layers_static()

    # -- partitioning ------------------------------------------------------
    def _layer_weights_by_class(self, regex):
        pattern = re.compile(regex)
        weights = []
        for f in self.forward_funcs:
            cls = type(f[0] if isinstance(f, tuple) else f).__name__
            weights.append(1.0 if pattern.search(cls) else 0.0)
        return weights

    def _partition_layers_static(self):
        """Partition without parameter counts (uniform / type:regex). The
        'parameters' method refines boundaries at init() when shapes are
        known (the reference counts torch params eagerly, module.py:388)."""
        method = (self.partition_method or "uniform").lower()
        if method.startswith("type:"):
            weights = self._layer_weights_by_class(method[5:])
            if sum(weights) == 0:
                weights = [1.0] * self._num_layers
            self.parts = partition_balanced(weights, self.num_stages)
        else:
            self.parts = partition_uniform(self._num_layers, self.num_stages)

    def _partition_layers_by_params(self, params):
        counts = []
        for i in range(self._num_layers):
            sub = params.get(f"layer_{i}", {})
            counts.append(sum(int(np.prod(p.shape))
                              for p in jax.tree_util.tree_leaves(sub)) + 1.0)
        self.parts = partition_balanced(counts, self.num_stages)
        for s in range(self.num_stages):
            logger.info(f"pipeline stage {s}: layers "
                        f"[{self.parts[s]}, {self.parts[s+1]}) "
                        f"params={sum(counts[self.parts[s]:self.parts[s+1]])/1e6:.2f}M")

    def stage_of_layer(self, layer_idx):
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        return self.num_stages - 1

    def stage_layers(self, stage_id):
        return list(range(self.parts[stage_id], self.parts[stage_id + 1]))

    # -- flax-like interface ----------------------------------------------
    def _apply_layer(self, idx, layer_params, x, tied_params):
        f = self.forward_funcs[idx]
        if isinstance(f, tuple):  # tied layer
            key, forward_fn = f
            module = self.tied_modules[key]
            p = tied_params[key]
            if forward_fn is not None:
                return forward_fn(module, p, x)
            return module.apply({"params": p}, x)
        if hasattr(f, "apply") and hasattr(f, "init"):
            return f.apply({"params": layer_params}, x)
        return f(x)

    def init(self, rng, x):
        params = {}
        tied = {}
        h = x
        for i, f in enumerate(self.forward_funcs):
            if self.seed_layers:
                rng = jax.random.fold_in(jax.random.PRNGKey(self.base_seed), i)
            if isinstance(f, tuple):
                key, forward_fn = f
                module = self.tied_modules[key]
                if key not in tied:
                    rng, sub = jax.random.split(rng)
                    tied[key] = module.init(sub, h)["params"]
                h = self._apply_layer(i, None, h, tied)
            elif hasattr(f, "init"):
                rng, sub = jax.random.split(rng)
                variables = f.init(sub, h)
                params[f"layer_{i}"] = variables.get("params", variables)
                h = self._apply_layer(i, params[f"layer_{i}"], h, tied)
            else:
                h = f(h)
        params["tied"] = tied
        if (self.partition_method or "").lower() == "parameters":
            self._partition_layers_by_params(params)
        return {"params": params}

    def apply(self, variables, x, **kwargs):
        params = variables["params"]
        tied = params.get("tied", {})
        h = x
        for i in range(self._num_layers):
            layer_params = params.get(f"layer_{i}")
            if self.activation_checkpoint_interval > 0 and \
                    i % self.activation_checkpoint_interval == 0:
                h = jax.checkpoint(
                    lambda p, hh, idx=i: self._apply_layer(idx, p, hh, tied)
                )(layer_params, h)
            else:
                h = self._apply_layer(i, layer_params, h, tied)
        return h

    def __call__(self, x):
        raise RuntimeError("PipelineModule must be used through an engine")

    def num_layers(self):
        return self._num_layers

    def topology_grid(self):
        return self.topology
