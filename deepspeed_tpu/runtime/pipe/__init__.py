from deepspeed_tpu.runtime.pipe.module import PipelineModule, LayerSpec, TiedLayerSpec
from deepspeed_tpu.runtime.pipe import schedule
