"""Pipeline instruction schedules — parity rebuild of
deepspeed/runtime/pipe/schedule.py:129,182,292 and the instruction vocabulary
(:336-474).

The generators yield, per step, a list of atomic instructions exactly like
the reference, so schedule behavior (buffer counts, send/recv pairing, 1F1B
interleave) is testable without hardware. On TPU the PipelineEngine lowers
each instruction to jitted stage programs + ppermute transfers instead of
p2p NCCL broadcasts.
"""

from abc import ABC, abstractmethod


class PipeInstruction:
    """Base instruction (reference schedule.py:336)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        if self.kwargs:
            args = ", ".join(f"{k}={v}" for k, v in sorted(self.kwargs.items()))
            return f"{self.name}({args})"
        return self.name

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule(ABC):
    """Base schedule generator (reference schedule.py:7-127)."""

    def __init__(self, micro_batches, stages, stage_id):
        super().__init__()
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = self.stage_id - 1
        self.next_stage = self.stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield a list-of-instructions per step."""
        raise NotImplementedError

    def num_pipe_buffers(self):
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    @property
    def stage(self):
        return self.stage_id

    @property
    def num_stages(self):
        return self.stages

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id):
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        self.it = None
        return self

    def __next__(self):
        if self.it is None:
            self.it = self.steps()
        return next(self.it)


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining (reference schedule.py:129)."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro_batch_id = step_id - self.stage_id
            cmds = []
            if self._valid_micro_batch(prev_micro_batch_id) and \
                    self._valid_stage(self.next_stage):
                cmds.append(SendActivation(self._buffer_idx(prev_micro_batch_id)))
            if self._valid_micro_batch(micro_batch_id):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(self._buffer_idx(micro_batch_id)))
                elif self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(self._buffer_idx(micro_batch_id)))
                cmds.append(ForwardPass(self._buffer_idx(micro_batch_id)))
            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B interleave, derived from the closed form the SPMD executor runs
    (parallel/pipeline_1f1b.py:90; behavioral contract = reference
    schedule.py:182): on stage s of S,

        fwd(m) computes at tick  2m + s
        bwd(m) computes at tick  2m + 2S - 1 - s

    Ticks therefore alternate direction per stage (fwd ticks share the
    stage's parity), and communication needs no separate bookkeeping: a
    tensor produced at tick t is shipped at tick t + 1, which by the same
    equations is exactly the tick the neighbor consumes it."""

    def steps(self):
        last_tick = 2 * (self.micro_batches + self.stages - 1) - 1
        prev = -1  # micro-batch computed on the previous tick (may be invalid)
        for tick in range(last_tick + 1):
            m, is_forward = self._step_to_micro_batch(tick)
            cmds = []
            if is_forward:
                # prev tick was a bwd: its input-cotangent goes upstream now,
                # while the upstream neighbor's fresh activation arrives.
                if self._valid_micro_batch(prev) and not self.is_first_stage:
                    cmds.append(SendGrad(self._buffer_idx(prev)))
                if self._valid_micro_batch(m):
                    if not self.is_first_stage:
                        cmds.append(RecvActivation(self._buffer_idx(m)))
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(self._buffer_idx(m)))
                    cmds.append(ForwardPass(self._buffer_idx(m)))
            else:
                # prev tick was a fwd: its activation goes downstream now,
                # while the downstream neighbor's cotangent arrives.
                if self._valid_micro_batch(m) and not self.is_last_stage:
                    cmds.append(RecvGrad(self._buffer_idx(m)))
                if self._valid_micro_batch(prev) and not self.is_last_stage:
                    cmds.append(SendActivation(self._buffer_idx(prev)))
                if self._valid_micro_batch(m):
                    cmds.append(BackwardPass(self._buffer_idx(m)))
            if tick == last_tick:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            prev = m
            yield cmds

    def num_pipe_buffers(self):
        """stages - stage_id + 1 buffers, ≤ micro_batches (reference
        :243-247)."""
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id):
        """Invert the tick equations for this stage: which micro-batch does
        tick `step_id` carry, and in which direction? The id is unclipped —
        fill/drain ticks yield ids outside [0, M) that callers skip."""
        is_forward = (step_id - self.stage_id) % 2 == 0
        if is_forward:
            micro_batch_id = (step_id - self.stage_id) // 2
        else:
            micro_batch_id = (step_id - (2 * self.stages - 1 - self.stage_id)) // 2
        return micro_batch_id, is_forward


class DataParallelSchedule(PipeSchedule):
    """Plain DP as a degenerate pipeline (reference schedule.py:292)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self):
        return 1
