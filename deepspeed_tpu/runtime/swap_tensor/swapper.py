"""Tensor swappers — rebuild of deepspeed/runtime/swap_tensor/
(partitioned_param_swapper.py:36, optimizer_utils.py:118,
pipelined_optimizer_swapper.py): NVMe residency for optimizer state and
parameters, powered by the native async-IO library (csrc/aio.cpp).

Layout: one file per (tensor, field) under ``<nvme_path>/zero_swap_<pid>/``;
double-buffered reads (``prefetch`` starts the async read of the next
tensor while the caller consumes the current one — the reference's
pipelined swapper overlap, pipelined_optimizer_swapper.py:60).

Pipelined schedules (PR 5, the reference's pipeline_read/pipeline_write
knobs made real): the param and optimizer swappers each own a SECOND aio
handle dedicated to write-behind — ``aio_handle_wait`` drains a whole
handle, so reads and writes must never share one — plus a bounded pool
of host staging buffers. A write-behind submission copies the leaf into
a pool buffer and returns immediately; the buffer then doubles as a
byte-exact cache of the file, so the next swap-in of a recently written
leaf is a host memcpy instead of a disk read. The drain fence
(``drain_writes``) runs before any pending leaf is re-read FROM DISK —
cache-served leaves need no fence because the staged bytes are the
authoritative copy the file was written from.

Swap files are preallocated (``ftruncate`` + ``posix_fallocate``) and
kept open without ``O_TRUNC`` across steps, so steady-state writes reuse
extents instead of reallocating them, and swap-in issues an
``fadvise(WILLNEED)`` readahead pass before reading — the first-epoch
read path runs at steady-state bandwidth instead of the 5x-slower
cold-file rate (BENCH_r05 ``aio_disk.first_read_mbps``).

All swap-path telemetry is sync-free (host wall timers + byte counters
into the process registry): ``swap/bytes_read``, ``swap/bytes_written``,
``swap/cache_hit_bytes`` counters, the ``swap/staging_bytes`` occupancy
gauge, and the per-step I/O-blocked seconds surfaced via
``take_stall_s()`` (the engine folds them into the ``swap/stall_s``
histogram).
"""

import os
import shutil
import time
import weakref

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _make_aio_handle(aio_config):
    """One construction point for the aio handle's tuning knobs — every
    swapper shares the same defaults, and the ``aio.o_direct`` knob
    reaches all four handle sites (park, read window, prefetch,
    write-behind) plus the snapshotter through here."""
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    cfg = aio_config
    return AsyncIOHandle(
        block_size=getattr(cfg, "block_size", 1 << 20),
        queue_depth=getattr(cfg, "queue_depth", 8),
        single_submit=getattr(cfg, "single_submit", False),
        overlap_events=getattr(cfg, "overlap_events", True),
        thread_count=getattr(cfg, "thread_count", 2),
        o_direct=getattr(cfg, "o_direct", False))


def _aligned_empty(nbytes):
    from deepspeed_tpu.ops.native.aio import aligned_empty
    return aligned_empty(nbytes)


def _fd_is_direct(fd):
    from deepspeed_tpu.ops.native.aio import fd_is_direct
    return fd_is_direct(fd)


def _fsync_dir(path):
    dfd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def sweep_stale_pid_dirs(nvme_path, prefix):
    """SIGKILL leaves pid-scoped scratch dirs behind — the weakref
    finalizers that normally rmtree them never run (ISSUE 20 fix).
    Reclaim any ``<prefix>_<pid>`` sibling whose pid is dead before
    creating ours; a pid we cannot signal (EPERM: alive, someone
    else's) is left alone."""
    try:
        names = os.listdir(nvme_path)
    except OSError:
        return []
    swept = []
    for name in names:
        if not name.startswith(prefix + "_"):
            continue
        tail = name.rsplit("_", 1)[-1]
        if not tail.isdigit() or int(tail) == os.getpid():
            continue
        try:
            os.kill(int(tail), 0)
        except ProcessLookupError:
            shutil.rmtree(os.path.join(nvme_path, name),
                          ignore_errors=True)
            swept.append(name)
        except OSError:
            continue
    if swept:
        logger.info("reclaimed %d stale swap scratch dir(s) under %s: %s",
                    len(swept), nvme_path, ", ".join(sorted(swept)))
    return swept


def _registry():
    from deepspeed_tpu.telemetry import default_registry
    return default_registry()


def _recorder():
    from deepspeed_tpu.telemetry import default_recorder
    return default_recorder()


def _close_fds_and_rm(path, fds, remove):
    """weakref.finalize target — must not reference the swapper. ``fds``
    is the LIVE dict (cleared by release(), so a later GC finalize never
    double-closes recycled fd numbers)."""
    for fd in list(fds.values()):
        try:
            os.close(fd)
        except OSError:
            pass
    fds.clear()
    if remove:
        shutil.rmtree(path, ignore_errors=True)


class TensorSwapper:
    """Owns the swap directory + aio handle; swaps named fp32 buffers."""

    def __init__(self, nvme_path, aio_config=None, sub_dir="zero_swap"):
        sweep_stale_pid_dirs(nvme_path, sub_dir)
        self.dir = os.path.join(nvme_path, f"{sub_dir}_{os.getpid()}")
        os.makedirs(self.dir, exist_ok=True)
        self.handle = _make_aio_handle(aio_config)
        self._pending_read = None  # (name, buffer, fd)
        # swap files are pid-scoped scratch — reclaim the NVMe space when
        # the swapper is garbage-collected or the process exits (a weakref
        # finalizer, unlike atexit.register(self.release), does not pin
        # the instance and its staging buffers for the process lifetime)
        self._finalizer = weakref.finalize(
            self, shutil.rmtree, self.dir, ignore_errors=True)

    def _path(self, name):
        return os.path.join(self.dir, f"{name}.swp")

    def _drain_pending(self):
        """Wait for the in-flight prefetch (if any) and close its fd."""
        if self._pending_read is None:
            return None, None
        name, buf, fd = self._pending_read
        self._pending_read = None
        try:
            self.handle.wait()
        finally:
            self.handle.close(fd)
        return name, buf

    def swap_out(self, name, array):
        assert array.dtype == np.float32 and array.flags["C_CONTIGUOUS"]
        # drain first: the handle's wait/error accounting is per-batch, so a
        # sync op must not share the handle with an in-flight prefetch (it
        # would absorb the prefetch's completion and error status)
        self._drain_pending()
        self.handle.sync_pwrite(array, self._path(name))

    def swap_in(self, name, out_array):
        if self._pending_read and self._pending_read[0] == name:
            _, buf = self._drain_pending()
            if buf is not out_array:
                np.copyto(out_array, buf)
            return out_array
        self._drain_pending()
        self.handle.sync_pread(out_array, self._path(name))
        return out_array

    def prefetch(self, name, out_array):
        """Start the async read of `name`; a following swap_in(name) waits
        and consumes it (double buffering)."""
        self._drain_pending()
        fd = self.handle.open(self._path(name), False)
        self.handle.async_pread(out_array, fd)
        self._pending_read = (name, out_array, fd)

    def release(self):
        try:
            self._drain_pending()
        except Exception:
            pass
        shutil.rmtree(self.dir, ignore_errors=True)


class _StagingArena:
    """Staging buffers for the swap path served from one contiguous arena
    (reference: stage 3 backs its fp16 partitions with the
    ContiguousMemoryAllocator and defragments on demand, stage3.py:1073).
    Live buffers are never moved — an async read may be in flight into
    them — so the arena only defragments when nothing is live; requests it
    cannot place contiguously fall back to a plain numpy allocation."""

    def __init__(self, slots=4, aligned=False):
        self.arena = None
        self._live = 0
        self._max_numel = 0
        # sized for ``slots`` leaves of the largest size seen — the
        # double-buffer minimum is 4 (2 Adam fields x 2 leaves in flight);
        # pipelined write-behind asks for more
        self._slots = max(4, int(slots))
        # page-aligned sub-allocations (ISSUE 20): slices handed to an
        # O_DIRECT aio handle start on page boundaries, so the aligned
        # body of every transfer submits zero-copy
        self._aligned = bool(aligned)

    def _align_elems(self):
        if not self._aligned:
            return 1
        from deepspeed_tpu.ops.native.aio import ALIGNMENT
        return ALIGNMENT // np.dtype(np.float32).itemsize

    def take(self, shape):
        """Returns (tid_or_None, float32 array of `shape`)."""
        from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import (
            ContiguousMemoryAllocator)
        numel = int(np.prod(shape))
        # grow to the LARGEST leaf seen whenever idle, so heterogeneous
        # leaf sizes converge on an arena that fits everything after one
        # full fetch/store cycle (first-leaf sizing would permanently
        # exile every bigger leaf to the numpy fallback)
        self._max_numel = max(self._max_numel, numel)
        ae = self._align_elems()
        slot_numel = -(-self._max_numel // ae) * ae
        if self.arena is None or (
                self._live == 0
                and self.arena.size < self._slots * slot_numel):
            self.arena = ContiguousMemoryAllocator(
                self._slots * slot_numel, np.float32, align_elems=ae)
        alloc = -(-numel // ae) * ae
        can_place = self.arena._largest_free() >= alloc or self._live == 0
        if not can_place or alloc > self.arena.total_free:
            if self._aligned:
                from deepspeed_tpu.ops.native.aio import aligned_empty
                flat = aligned_empty(numel * 4).view(np.float32)
                return None, flat.reshape(shape)
            return None, np.empty(shape, np.float32)
        tid, view = self.arena.allocate_tensor(numel)
        self._live += 1
        return tid, view.reshape(shape)

    def give(self, tid):
        if tid is not None:
            self.arena.release_tensor(tid)
            self._live -= 1


class PartitionedParamSwapper:
    """NVMe-resident model parameters — the ZeRO-Infinity parameter tier
    (reference swap_tensor/partitioned_param_swapper.py:36). Compute-dtype
    param leaves rest in one file each; around every step they stream

        disk --aio read--> bounded staging (buffer_count) --device_put--> HBM

    with the disk read of leaf group k+1 overlapping the h2d put of group
    k (sliding read window over ``buffer_count`` staging slots), and after
    the update HBM → staging → disk. Host RSS for parameters is therefore
    bounded by ``buffer_count`` read slots + ``buffer_count`` write-behind
    buffers of the largest leaf regardless of model size — the reference's
    pinned-buffer-count bound.

    ``pipeline_write`` turns the post-step park into write-behind: leaves
    are copied into pool buffers and the aio writes run on a dedicated
    handle while the caller proceeds (the swap-out of step N overlaps
    whatever follows — the optimizer tail, telemetry, and the next step's
    swap-in). ``drain_writes()`` is the durability fence; it runs
    automatically before any pending leaf would be re-read from disk.
    The pool buffers double as a byte cache of the just-written files, so
    the next swap-in serves recently written leaves from host memory.
    """

    def __init__(self, nvme_path, aio_config=None, sub_dir=None,
                 durable=False, pipeline_read=False, pipeline_write=False,
                 buffer_count=2, registry=None, fsync=False):
        """``sub_dir``/``durable``: by default the swap files are
        pid-scoped SCRATCH (reclaimed on GC/exit). A durable tier (the
        ZeRO-Infinity at-rest files, runtime/zero/infinity.py) passes a
        stable sub_dir and durable=True: files survive the process and
        carry a meta.json sidecar so a fresh process can restore."""
        if sub_dir is None:
            sweep_stale_pid_dirs(nvme_path, "param_swap")
        self.dir = os.path.join(
            nvme_path, sub_dir or f"param_swap_{os.getpid()}")
        os.makedirs(self.dir, exist_ok=True)
        self.handle = _make_aio_handle(aio_config)
        self._aio_config = aio_config
        self.meta = {}            # leaf idx -> (shape, numpy dtype)
        self.pipeline_read = bool(pipeline_read)
        self.pipeline_write = bool(pipeline_write)
        self.buffer_count = max(2, int(buffer_count))
        self._staging = [None] * (self.buffer_count if pipeline_read else 2)
        self._durable = durable
        # -- write-behind state (pipeline_write) ---------------------------
        self._whandle = None      # dedicated aio handle, lazily built
        self._wpool = []          # staging buffers (np.uint8)
        self._wbusy = set()       # pool indices with an in-flight write
        self._cache = {}          # leaf idx -> (pool idx, nbytes)
        self._pending = set()     # leaf idx with a not-yet-drained write
        self._wfds = {}           # leaf idx -> preallocated write fd
        self._fsizes = {}         # leaf idx -> preallocated byte size
        # fsync-fenced durability (ISSUE 7 satellite): without it the
        # swap files ride the guest page cache and the drain fence only
        # orders THIS process's reads after its writes; with it the
        # fence is a real durability barrier — elastic snapshots that
        # copy parked files require this mode on the param tier
        self.fsync = bool(fsync)
        self._stall_s = 0.0
        self._registry = registry
        self._finalizer = weakref.finalize(
            self, _close_fds_and_rm, self.dir, self._wfds,
            remove=not durable)

    def _path(self, i):
        return os.path.join(self.dir, f"param_{i}.swp")

    def _meta_path(self):
        return os.path.join(self.dir, "meta.json")

    def save_meta(self):
        import json
        with open(self._meta_path(), "w") as f:
            json.dump({str(i): [list(s), str(np.dtype(d))]
                       for i, (s, d) in self.meta.items()}, f)

    def load_meta(self):
        """Restore leaf metadata written by a previous process's
        write_all (durable tiers only)."""
        import json
        with open(self._meta_path()) as f:
            raw = json.load(f)
        self.meta = {int(i): (tuple(s), np.dtype(d))
                     for i, (s, d) in raw.items()}
        return self.meta

    # -- telemetry (sync-free: host counters/timers only) ------------------
    def _reg(self):
        if self._registry is None:
            self._registry = _registry()
        return self._registry

    def take_stall_s(self):
        """I/O-blocked host seconds accumulated since the last call —
        time the caller's thread actually waited on disk (sync ops +
        drain fences), NOT time I/O spent overlapped with other work."""
        s, self._stall_s = self._stall_s, 0.0
        return s

    def _timed_wait(self, handle):
        t0 = time.perf_counter()
        try:
            handle.wait()
        finally:
            self._stall_s += time.perf_counter() - t0

    def _staging_bytes(self):
        return sum(b.nbytes for b in self._wpool) + sum(
            b.nbytes for b in self._staging if b is not None)

    # -- file lifecycle: preallocated, no O_TRUNC churn --------------------
    def _write_fd(self, i, nbytes):
        """Cached write fd for leaf ``i``'s file, preallocated to its
        I/O size: steady-state writes reuse extents (no per-step
        truncate/alloc). Buffered mode preallocates byte-exact; under
        O_DIRECT the physical size rounds up to the page (aligned
        extents — transfer lengths must be aligned, so readers request
        the rounded length and slice the exact bytes via ``meta``)."""
        fd = self._wfds.get(i)
        if fd is None:
            fd = self.handle.open_fd(self._path(i),
                                     os.O_WRONLY | os.O_CREAT)
            self._wfds[i] = fd
        alloc = self.handle.io_nbytes(nbytes)
        if self._fsizes.get(i) != alloc:
            os.ftruncate(fd, alloc)
            try:
                os.posix_fallocate(fd, 0, alloc)
            except OSError:
                pass  # fs without fallocate: sparse until first write
            if self.fsync and _fd_is_direct(fd):
                # the one metadata fsync this file needs: the direct
                # writes themselves bypass the cache, but the size/
                # extent change from this preallocation does not
                os.fsync(fd)
            self._fsizes[i] = alloc
        return fd

    def _readahead(self, indices):
        """fadvise(WILLNEED) the files about to be read — kernel
        readahead fills the page cache while earlier leaves process, so
        the first epoch reads at steady-state bandwidth (the BENCH_r05
        first_read_mbps=298-vs-1640 fix). Under active O_DIRECT there
        is no page cache to warm — the pass would be a pure syscall tax
        per file per window, so it is gated off entirely."""
        if self.handle.direct_active:
            return
        for i in indices:
            try:
                fd = os.open(self._path(i), os.O_RDONLY)
            except OSError:
                continue
            try:
                os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_WILLNEED)
            except OSError:
                pass
            finally:
                os.close(fd)

    @staticmethod
    def _as_bytes(arr):
        return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)

    def write_all(self, leaves):
        """Initial population / re-park after checkpoint load: every leaf
        (device or host) → its preallocated file. Sync writes; called off
        the step path. Ends with a readahead pass so the first swap-in is
        not cold-file-bound. ``leaves`` may be any iterable — a generator
        keeps host residency at one leaf while parking a >RAM model
        (the nvme_xl path)."""
        self.drain_writes()
        self._cache.clear()
        n = 0
        for i, leaf in enumerate(leaves):
            arr = np.ascontiguousarray(np.asarray(leaf))  # sync-ok: d2h park
            self.meta[i] = (arr.shape, arr.dtype)
            b = self._as_bytes(arr)
            t0 = time.perf_counter()
            self.handle.sync_pwrite(b, self._write_fd(i, b.nbytes))
            self._stall_s += time.perf_counter() - t0
            self._reg().counter("swap/bytes_written").inc(b.nbytes)
            n = i + 1
        if self._durable:
            self.save_meta()
        self._readahead(range(n))

    # -- write-behind ------------------------------------------------------
    def _take_wbuf(self, nbytes):
        """A pool buffer free for a new write: not in flight, preferring
        one that backs no cache entry; evicts the oldest cache entry when
        the pool is full; drains the write handle when every buffer is
        busy. Pool is bounded at ``buffer_count`` buffers of the largest
        leaf size seen."""
        alloc = self.handle.io_nbytes(nbytes)
        backing = {idx for idx, _ in self._cache.values()}
        for attempt in range(2):
            free = [k for k in range(len(self._wpool))
                    if k not in self._wbusy and k not in backing]
            if not free and len(self._wpool) < self.buffer_count:
                self._wpool.append(_aligned_empty(alloc))
                return len(self._wpool) - 1
            if not free:
                # evict the oldest cached leaf whose buffer is idle
                for leaf, (idx, _) in list(self._cache.items()):
                    if idx not in self._wbusy:
                        del self._cache[leaf]
                        free = [idx]
                        break
            if free:
                idx = free[0]
                if self._wpool[idx].nbytes < alloc:
                    self._wpool[idx] = _aligned_empty(alloc)
                return idx
            # every buffer carries an in-flight write: fence and retry
            self.drain_writes()
            backing = {idx for idx, _ in self._cache.values()}
        raise RuntimeError("write-behind pool exhausted after drain")

    def _write_handle(self):
        if self._whandle is None:
            self._whandle = _make_aio_handle(self._aio_config)
        return self._whandle

    def write_behind(self, i, host_arr):
        """Queue the async write of leaf ``i`` (bytes are copied into a
        pool buffer — the caller may reuse ``host_arr`` immediately) and
        return without waiting. The pool copy stays registered as a byte
        cache of the file, so a following swap-in of this leaf is a host
        memcpy. ``drain_writes`` (automatic before any disk re-read of a
        pending leaf) is the durability fence."""
        arr = np.ascontiguousarray(np.asarray(host_arr))  # sync-ok: d2h park
        if i in self._pending:
            # a second write of the same leaf must not race the first on
            # the same fd (completion order is not defined)
            self.drain_writes()
        self.meta[i] = (arr.shape, arr.dtype)
        b = arr.view(np.uint8).reshape(-1)
        idx = self._take_wbuf(b.nbytes)
        buf = self._wpool[idx][:b.nbytes]
        np.copyto(buf, b)
        # submit the handle's physical length: under O_DIRECT that is
        # the aligned slice of the (page-aligned) pool buffer — a
        # zero-copy submission; buffered mode submits the exact bytes
        wlen = self.handle.io_nbytes(b.nbytes)
        if wlen > b.nbytes:
            self._wpool[idx][b.nbytes:wlen] = 0
        self._write_handle().async_pwrite(self._wpool[idx][:wlen],
                                          self._write_fd(i, b.nbytes))
        self._wbusy.add(idx)
        self._cache[i] = (idx, b.nbytes)
        self._pending.add(i)
        reg = self._reg()
        reg.counter("swap/bytes_written").inc(b.nbytes)
        reg.gauge("swap/staging_bytes").set_max(self._staging_bytes())

    def drain_writes(self):
        """Fence: wait for every in-flight write-behind. Cheap no-op when
        nothing is pending. With ``fsync`` on, the fence additionally
        makes the just-written files durable: buffered fds get a data
        fsync each; O_DIRECT fds need none (completed direct writes are
        on the device) — only the DIRENT durability remains, one
        directory fsync per drain instead of a per-file data flush."""
        if not self._pending and not self._wbusy:
            return
        n = len(self._pending)
        t0 = time.perf_counter()
        self._timed_wait(self._write_handle())
        if self.fsync:
            t1 = time.perf_counter()
            need_dirent = False
            for i in self._pending:
                fd = self._wfds.get(i)
                if fd is None:
                    continue
                if _fd_is_direct(fd):
                    need_dirent = True
                else:
                    os.fsync(fd)
            if need_dirent:
                _fsync_dir(self.dir)
            self._stall_s += time.perf_counter() - t1
        self._wbusy.clear()
        self._pending.clear()
        _recorder().record("swap_drain", leaves=n, fsync=self.fsync,
                           o_direct=self.handle.direct_active,
                           wait_s=time.perf_counter() - t0)

    @property
    def has_pending_writes(self):
        return bool(self._pending)

    def staged_leaf(self, i):
        """Snapshot-path access to a parked leaf (ISSUE 7): returns
        ``(value, source)`` where ``value`` is a host ndarray view of
        the write-behind staging cache (``source="cache"`` — valid
        only until the next park reuses the pool, so callers must
        consume/copy it before returning to training) or the swap-file
        path (``source="file"``). Callers must ``drain_writes()``
        first while ``has_pending_writes`` — a pending file is not
        whole yet. This is the supported API for reading parked bytes;
        the pool/cache internals it wraps are free to change."""
        shape, dtype = self.meta[i]
        c = self._cache.get(i)
        if c is not None:
            idx, nbytes = c
            return self._host_view(self._wpool[idx][:nbytes], i), "cache"
        return self._path(i), "file"

    # -- the swap schedule -------------------------------------------------
    def _stage(self, slot, nbytes):
        """Staging slot sized to the handle's physical I/O length —
        page-aligned mmap buffers, so O_DIRECT reads of the aligned
        slice land zero-copy (``_host_view`` slices the exact leaf
        bytes back out)."""
        need = self.handle.io_nbytes(nbytes)
        buf = self._staging[slot]
        if buf is None or buf.nbytes < need:
            self._staging[slot] = buf = _aligned_empty(need)
        return buf[:need]

    def _leaf_nbytes(self, i):
        shape, dtype = self.meta[i]
        return int(np.prod(shape or (1,))) * dtype.itemsize

    def _host_view(self, raw, i):
        shape, dtype = self.meta[i]
        return raw[:self._leaf_nbytes(i)].view(dtype).reshape(shape)

    def swap_in_device(self, shardings, order=None):
        """disk → device params; returns the list of device leaves.

        ``order`` (a permutation of leaf indices) is the per-layer swap
        schedule: leaves stream in the order compute will consume them.
        Recently write-behind-parked leaves are served from the pool
        cache (host memcpy, no disk read, no fence needed — the staged
        bytes are what the file was written from); the rest read through
        a sliding window of ``len(self._staging)`` staging slots so the
        disk read of group k+1 overlaps the host/h2d processing of
        group k."""
        import jax
        n = len(self.meta)
        outs = [None] * n
        if n == 0:
            return outs
        order = list(order) if order is not None else list(range(n))
        assert sorted(order) == list(range(n)), order
        # CPU device_put aliases host memory — a reused staging buffer
        # would corrupt the "device" params. Decide from the TARGET
        # devices (an engine may run a CPU mesh under a TPU default)
        aliases_host = shardings[0].mesh.devices.flat[0].platform == "cpu"
        reg = self._reg()

        disk = [i for i in order if i not in self._cache]
        cached = [i for i in order if i in self._cache]
        self._readahead(disk)

        # cache-served leaves process FIRST, while the write-behind of the
        # previous park is still in flight — the staged bytes are the
        # authoritative copy, so no fence is needed for them
        for i in cached:
            idx, nbytes = self._cache[i]
            view = self._host_view(self._wpool[idx][:nbytes], i)
            # non-aliasing backends: device_put copies and the end-of-
            # call fence protects the pool view until the h2d lands, so
            # only the aliasing CPU backend needs the private copy
            host = np.array(view, copy=True) if aliases_host else view
            outs[i] = jax.device_put(host, shardings[i])
            reg.counter("swap/cache_hit_bytes").inc(nbytes)

        if self._pending.intersection(disk):
            # durability fence: a pending write's file must be whole
            # before it is re-read from disk
            self.drain_writes()

        slots = len(self._staging)
        group = max(1, slots // 2)
        groups = [disk[k:k + group] for k in range(0, len(disk), group)]
        fds = {}

        def submit(gi):
            for j, i in enumerate(groups[gi]):
                slot = (gi * group + j) % slots
                buf = self._stage(slot, self._leaf_nbytes(i))
                fds[i] = self.handle.open(self._path(i), False)
                self.handle.async_pread(buf, fds[i])

        if groups:
            submit(0)

        for gi, g in enumerate(groups):
            self._timed_wait(self.handle)
            for i in g:
                self.handle.close(fds.pop(i))
            if gi + 1 < len(groups):
                if not aliases_host and gi >= 1:
                    # group gi+1 reuses group gi-1's slots: their h2d
                    # puts must have consumed the staging bytes
                    for i in groups[gi - 1]:
                        outs[i].block_until_ready()  # sync-ok: slot reuse
                submit(gi + 1)  # reads overlap the puts below
            for j, i in enumerate(g):
                slot = (gi * group + j) % slots
                arr = self._host_view(self._staging[slot], i)
                host = np.array(arr, copy=True) if aliases_host else arr
                outs[i] = jax.device_put(host, shardings[i])
                reg.counter("swap/bytes_read").inc(self._leaf_nbytes(i))
        reg.gauge("swap/staging_bytes").set_max(self._staging_bytes())
        if not aliases_host:
            for o in outs:
                o.block_until_ready()  # sync-ok: staging reuse safety
        _recorder().record(
            "swap_in", leaves=n,
            bytes_read=sum(self._leaf_nbytes(i) for i in disk),
            cache_hit_bytes=sum(self._cache[i][1] for i in cached
                                if i in self._cache))
        return outs

    def swap_in_stream(self, order=None):
        """Generator form of the read schedule for layer-streamed
        consumers (ISSUE 20's >RAM-scale path): yields ``(i, host_view)``
        in ``order`` with the same sliding staging window as
        ``swap_in_device`` but NO device materialization — host residency
        stays bounded by the staging slots no matter the model size. The
        yielded view aliases a staging slot and is valid only until the
        window advances past it (consume or copy before the next
        ``len(self._staging) // 2`` items)."""
        n = len(self.meta)
        order = list(order) if order is not None else list(range(n))
        if not order:
            return
        if self._pending.intersection(order):
            self.drain_writes()
        self._readahead([i for i in order if i not in self._cache])
        reg = self._reg()
        slots = len(self._staging)
        group = max(1, slots // 2)
        groups = [order[k:k + group] for k in range(0, len(order), group)]
        fds = {}

        def submit(gi):
            for j, i in enumerate(groups[gi]):
                slot = (gi * group + j) % slots
                buf = self._stage(slot, self._leaf_nbytes(i))
                fds[i] = self.handle.open(self._path(i), False)
                self.handle.async_pread(buf, fds[i])

        submit(0)
        for gi, g in enumerate(groups):
            self._timed_wait(self.handle)
            for i in g:
                self.handle.close(fds.pop(i))
            if gi + 1 < len(groups):
                submit(gi + 1)   # next group's reads overlap the yields
            for j, i in enumerate(g):
                slot = (gi * group + j) % slots
                reg.counter("swap/bytes_read").inc(self._leaf_nbytes(i))
                yield i, self._host_view(self._staging[slot], i)

    def swap_out_device(self, leaves, write_behind=None):
        """device params → disk; frees nothing itself (callers delete the
        device arrays after). d2h transfers for all leaves start up front
        so later copies overlap earlier writes; with ``write_behind`` the
        disk writes run asynchronously on the dedicated handle and this
        returns as soon as the d2h copies land in the pool."""
        wb = self.pipeline_write if write_behind is None else write_behind
        for leaf in leaves:
            if hasattr(leaf, "copy_to_host_async"):
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass
        for i, leaf in enumerate(leaves):
            if wb:
                self.write_behind(i, leaf)
                continue
            if i in self._pending:
                # same-fd race guard, mirroring write_behind: a sync
                # write must not overlap an undrained async one
                self.drain_writes()
            arr = np.ascontiguousarray(np.asarray(leaf))  # sync-ok: d2h park
            self.meta[i] = (arr.shape, arr.dtype)
            b = self._as_bytes(arr)
            t0 = time.perf_counter()
            fd = self._write_fd(i, b.nbytes)
            self.handle.sync_pwrite(b, fd)
            if self.fsync and not _fd_is_direct(fd):
                os.fsync(fd)   # direct writes are on-device already
            self._stall_s += time.perf_counter() - t0
            self._cache.pop(i, None)  # staged bytes (if any) are stale
            self._reg().counter("swap/bytes_written").inc(b.nbytes)
        if self._durable:
            self.save_meta()
        _recorder().record(
            "swap_out", leaves=len(leaves), write_behind=bool(wb),
            bytes=sum(self._leaf_nbytes(i) for i in range(len(leaves))
                      if i in self.meta))

    def release(self):
        try:
            self.drain_writes()
        except Exception:
            pass
        for fd in list(self._wfds.values()):
            try:
                os.close(fd)
            except OSError:
                pass
        self._wfds.clear()   # the GC finalizer sees the emptied dict
        self._cache.clear()
        shutil.rmtree(self.dir, ignore_errors=True)


class OptimizerStateSwapper:
    """NVMe-resident Adam moments (the ZeRO-Infinity optimizer tier —
    reference optimizer_utils.py:118). Reads are double-buffered on a
    DEDICATED aio handle (the reference's PipelinedOptimizerSwapper
    overlap, pipelined_optimizer_swapper.py:60): ``prefetch(next_leaf)``
    starts the async read of the next leaf's moments while the caller
    computes on the current one. With ``pipeline_write`` the stores are
    write-behind on a third handle (the updated moments copy into a
    bounded pool and the writes overlap the next leaves' SIMD steps);
    otherwise writes stay sync on the main handle. Staging buffers come
    from a contiguous arena (_StagingArena) instead of per-call numpy
    churn."""

    FIELDS = ("exp_avg", "exp_avg_sq")

    def __init__(self, nvme_path, aio_config=None, pipeline_write=False,
                 buffer_count=2, registry=None):
        self.swapper = TensorSwapper(nvme_path, aio_config, "optimizer_swap")
        self.shapes = {}
        self._aio_config = aio_config
        self._pf_handle = _make_aio_handle(aio_config)
        self._pf = None  # (leaf_id, [bufs], [fds], [tids])
        self.pipeline_write = bool(pipeline_write)
        self.buffer_count = max(2, int(buffer_count))
        # write-behind pool sized for buffer_count leaves x 2 fields over
        # the shared arena; the arena grows to slots x largest-leaf
        self._arena = _StagingArena(
            slots=4 + (2 * self.buffer_count if pipeline_write else 0),
            aligned=getattr(aio_config, "o_direct", False))
        self._consumed = {}  # leaf_id -> [tids] handed out by fetch()
        self._wb_handle = None
        # in-flight write sources: (leaf_id, [tids], [arrays]) — the
        # array refs keep numpy-fallback staging alive until the drain
        # (the aio thread reads from those buffers)
        self._wb_live = []
        self._wb_pending = set()
        self._wb_fds = {}    # (leaf_id, field) -> preallocated write fd
        self._wb_sizes = {}
        self._registry = registry
        self._stall_s = 0.0
        self._fd_finalizer = weakref.finalize(
            self, _close_fds_and_rm, self.swapper.dir, self._wb_fds,
            remove=False)

    def _reg(self):
        if self._registry is None:
            self._registry = _registry()
        return self._registry

    def take_stall_s(self):
        s, self._stall_s = self._stall_s, 0.0
        return s

    def init_state(self, leaf_id, shape):
        self.shapes[leaf_id] = tuple(shape)
        zeros = np.zeros(shape, np.float32)
        for field in self.FIELDS:
            self.swapper.swap_out(f"{leaf_id}.{field}", zeros)

    def _drain_prefetch(self):
        if self._pf is None:
            return None
        leaf_id, bufs, fds, tids = self._pf
        self._pf = None
        t0 = time.perf_counter()
        try:
            self._pf_handle.wait()
        finally:
            self._stall_s += time.perf_counter() - t0
            for fd in fds:
                self._pf_handle.close(fd)
        return leaf_id, bufs, tids

    def _discard_prefetch(self):
        drained = self._drain_prefetch()
        if drained is not None:
            for tid in drained[2]:
                self._arena.give(tid)

    def _release_consumed(self, leaf_id):
        for tid in self._consumed.pop(leaf_id, ()):
            self._arena.give(tid)

    def drain_writes(self):
        """Fence for the write-behind stores: wait, then release the
        arena slots that backed the in-flight writes."""
        if not self._wb_live:
            return
        t0 = time.perf_counter()
        try:
            self._wb_handle.wait()
        finally:
            self._stall_s += time.perf_counter() - t0
        for _, tids, _arrs in self._wb_live:
            for tid in tids:
                self._arena.give(tid)
        self._wb_live = []
        self._wb_pending.clear()

    def prefetch(self, leaf_id):
        """Start the async read of ``leaf_id``'s moments; the matching
        fetch() consumes them without blocking on the disk."""
        if self._pf is not None and self._pf[0] == leaf_id:
            return
        if leaf_id in self._wb_pending:
            # the moments about to be read are still being written
            self.drain_writes()
        self._discard_prefetch()
        shape = self.shapes[leaf_id]
        bufs, fds, tids = [], [], []
        for field in self.FIELDS:
            tid, buf = self._arena.take(shape)
            fd = self._pf_handle.open(
                self.swapper._path(f"{leaf_id}.{field}"), False)
            self._pf_handle.async_pread(buf, fd)
            bufs.append(buf)
            fds.append(fd)
            tids.append(tid)
        self._pf = (leaf_id, bufs, fds, tids)

    def fetch(self, leaf_id):
        # a re-fetch without an intervening store (e.g. state_dict() walks
        # every leaf read-only) must not orphan the previous staging slots
        self._release_consumed(leaf_id)
        if leaf_id in self._wb_pending:
            self.drain_writes()
        if self._pf is not None and self._pf[0] == leaf_id:
            _, bufs, tids = self._drain_prefetch()
            self._consumed[leaf_id] = tids
            return bufs
        self._discard_prefetch()
        shape = self.shapes[leaf_id]
        out, tids = [], []
        t0 = time.perf_counter()
        for field in self.FIELDS:
            tid, buf = self._arena.take(shape)
            self.swapper.swap_in(f"{leaf_id}.{field}", buf)
            out.append(buf)
            tids.append(tid)
        self._stall_s += time.perf_counter() - t0
        self._consumed[leaf_id] = tids
        return out

    def store(self, leaf_id, exp_avg, exp_avg_sq):
        if self.pipeline_write:
            return self._store_behind(leaf_id, exp_avg, exp_avg_sq)
        t0 = time.perf_counter()
        self.swapper.swap_out(f"{leaf_id}.exp_avg", exp_avg)
        self.swapper.swap_out(f"{leaf_id}.exp_avg_sq", exp_avg_sq)
        self._stall_s += time.perf_counter() - t0
        self._reg().counter("swap/bytes_written").inc(
            exp_avg.nbytes + exp_avg_sq.nbytes)
        # the fetched staging views are dead once the new moments hit disk
        self._release_consumed(leaf_id)

    def _store_behind(self, leaf_id, exp_avg, exp_avg_sq):
        """Write-behind store: the updated moments usually ARE the arena
        views handed out by fetch() (the SIMD step updates them in
        place) — hand exactly those slots to the write handle and defer
        their release to the drain, so no extra copy happens; foreign
        arrays are copied into fresh arena slots first."""
        if leaf_id in self._wb_pending:
            self.drain_writes()  # same-fd write race guard
        elif len(self._wb_live) >= self.buffer_count:
            # bound the live staged moments at ~buffer_count leaves (the
            # documented pool bound): without this reap, a whole step's
            # stores stay live until the next step's first prefetch —
            # host RSS = total moment bytes, not the pool
            self.drain_writes()
        mine = self._consumed.pop(leaf_id, None)
        arrs = [np.ascontiguousarray(exp_avg, np.float32),
                np.ascontiguousarray(exp_avg_sq, np.float32)]
        if mine is not None and arrs[0] is exp_avg and arrs[1] is exp_avg_sq:
            tids = mine
        else:
            # foreign buffers (or a copy was forced): stage them
            if mine is not None:
                for tid in mine:
                    self._arena.give(tid)
            tids = []
            staged = []
            for a in arrs:
                tid, buf = self._arena.take(a.shape)
                np.copyto(buf, a)
                tids.append(tid)
                staged.append(buf)
            arrs = staged
        wh = self._wb_handle
        if wh is None:
            wh = self._wb_handle = _make_aio_handle(self._aio_config)
        for field, a in zip(self.FIELDS, arrs):
            wh.async_pwrite(a, self._wb_fd(leaf_id, field, a.nbytes))
        self._wb_live.append((leaf_id, tids, arrs))
        self._wb_pending.add(leaf_id)
        self._reg().counter("swap/bytes_written").inc(
            sum(a.nbytes for a in arrs))

    def _wb_fd(self, leaf_id, field, nbytes):
        """Cached no-O_TRUNC write fd per moment file, preallocated so
        steady-state stores reuse extents (the TensorSwapper sync path
        reopens with O_TRUNC each step — fine off the hot path)."""
        key = (leaf_id, field)
        handle = self.swapper.handle
        fd = self._wb_fds.get(key)
        if fd is None:
            fd = handle.open_fd(self.swapper._path(f"{leaf_id}.{field}"),
                                os.O_WRONLY | os.O_CREAT)
            self._wb_fds[key] = fd
        alloc = handle.io_nbytes(nbytes)
        if self._wb_sizes.get(key) != alloc:
            os.ftruncate(fd, alloc)
            try:
                os.posix_fallocate(fd, 0, alloc)
            except OSError:
                pass
            self._wb_sizes[key] = alloc
        return fd

    def release(self):
        try:
            self._discard_prefetch()
        except Exception:
            pass
        try:
            self.drain_writes()
        except Exception:
            pass
        for leaf in list(self._consumed):
            self._release_consumed(leaf)
        for fd in list(self._wb_fds.values()):
            try:
                os.close(fd)
            except OSError:
                pass
        self._wb_fds.clear()
        self.swapper.release()
