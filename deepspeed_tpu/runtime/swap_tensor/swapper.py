"""Tensor swappers — rebuild of deepspeed/runtime/swap_tensor/
(partitioned_param_swapper.py:36, optimizer_utils.py:118,
pipelined_optimizer_swapper.py): NVMe residency for optimizer state and
parameters, powered by the native async-IO library (csrc/aio.cpp).

Layout: one file per (tensor, field) under ``<nvme_path>/zero_swap_<pid>/``;
double-buffered reads (``prefetch`` starts the async read of the next
tensor while the caller consumes the current one — the reference's
pipelined swapper overlap, pipelined_optimizer_swapper.py:60).
"""

import os
import shutil
import weakref

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _make_aio_handle(aio_config):
    """One construction point for the aio handle's tuning knobs — every
    swapper shares the same defaults."""
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    cfg = aio_config
    return AsyncIOHandle(
        block_size=getattr(cfg, "block_size", 1 << 20),
        queue_depth=getattr(cfg, "queue_depth", 8),
        single_submit=getattr(cfg, "single_submit", False),
        overlap_events=getattr(cfg, "overlap_events", True),
        thread_count=getattr(cfg, "thread_count", 2))


class TensorSwapper:
    """Owns the swap directory + aio handle; swaps named fp32 buffers."""

    def __init__(self, nvme_path, aio_config=None, sub_dir="zero_swap"):
        self.dir = os.path.join(nvme_path, f"{sub_dir}_{os.getpid()}")
        os.makedirs(self.dir, exist_ok=True)
        self.handle = _make_aio_handle(aio_config)
        self._pending_read = None  # (name, buffer, fd)
        # swap files are pid-scoped scratch — reclaim the NVMe space when
        # the swapper is garbage-collected or the process exits (a weakref
        # finalizer, unlike atexit.register(self.release), does not pin
        # the instance and its staging buffers for the process lifetime)
        self._finalizer = weakref.finalize(
            self, shutil.rmtree, self.dir, ignore_errors=True)

    def _path(self, name):
        return os.path.join(self.dir, f"{name}.swp")

    def _drain_pending(self):
        """Wait for the in-flight prefetch (if any) and close its fd."""
        if self._pending_read is None:
            return None, None
        name, buf, fd = self._pending_read
        self._pending_read = None
        try:
            self.handle.wait()
        finally:
            self.handle.close(fd)
        return name, buf

    def swap_out(self, name, array):
        assert array.dtype == np.float32 and array.flags["C_CONTIGUOUS"]
        # drain first: the handle's wait/error accounting is per-batch, so a
        # sync op must not share the handle with an in-flight prefetch (it
        # would absorb the prefetch's completion and error status)
        self._drain_pending()
        self.handle.sync_pwrite(array, self._path(name))

    def swap_in(self, name, out_array):
        if self._pending_read and self._pending_read[0] == name:
            _, buf = self._drain_pending()
            if buf is not out_array:
                np.copyto(out_array, buf)
            return out_array
        self._drain_pending()
        self.handle.sync_pread(out_array, self._path(name))
        return out_array

    def prefetch(self, name, out_array):
        """Start the async read of `name`; a following swap_in(name) waits
        and consumes it (double buffering)."""
        self._drain_pending()
        fd = self.handle.open(self._path(name), False)
        self.handle.async_pread(out_array, fd)
        self._pending_read = (name, out_array, fd)

    def release(self):
        try:
            self._drain_pending()
        except Exception:
            pass
        shutil.rmtree(self.dir, ignore_errors=True)


class _StagingArena:
    """Staging buffers for the swap path served from one contiguous arena
    (reference: stage 3 backs its fp16 partitions with the
    ContiguousMemoryAllocator and defragments on demand, stage3.py:1073).
    Live buffers are never moved — an async read may be in flight into
    them — so the arena only defragments when nothing is live; requests it
    cannot place contiguously fall back to a plain numpy allocation."""

    def __init__(self):
        self.arena = None
        self._live = 0
        self._max_numel = 0

    def take(self, shape):
        """Returns (tid_or_None, float32 array of `shape`)."""
        from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import (
            ContiguousMemoryAllocator)
        numel = int(np.prod(shape))
        # grow to the LARGEST leaf seen whenever idle, so heterogeneous
        # leaf sizes converge on an arena that fits everything after one
        # full fetch/store cycle (first-leaf sizing would permanently
        # exile every bigger leaf to the numpy fallback)
        self._max_numel = max(self._max_numel, numel)
        if self.arena is None or (self._live == 0
                                  and self.arena.size < 4 * self._max_numel):
            # size for double-buffering both Adam moments (2 fields x 2
            # leaves in flight)
            self.arena = ContiguousMemoryAllocator(4 * self._max_numel,
                                                   np.float32)
        can_place = self.arena._largest_free() >= numel or self._live == 0
        if not can_place or numel > self.arena.total_free:
            return None, np.empty(shape, np.float32)
        tid, view = self.arena.allocate_tensor(numel)
        self._live += 1
        return tid, view.reshape(shape)

    def give(self, tid):
        if tid is not None:
            self.arena.release_tensor(tid)
            self._live -= 1


class PartitionedParamSwapper:
    """NVMe-resident model parameters — the ZeRO-Infinity parameter tier
    (reference swap_tensor/partitioned_param_swapper.py:36). Compute-dtype
    param leaves rest in one file each; around every step they stream

        disk --aio read--> bounded staging (2 buffers) --device_put--> HBM

    with the disk read of leaf i+1 overlapping the h2d put of leaf i
    (double buffering: the put of leaf i must complete before buffer
    i%2 is reused at leaf i+2 — enforced with a readiness fence), and
    after the update HBM → staging → disk with the d2h of later leaves
    overlapping earlier writes. Host RSS for parameters is therefore
    bounded by TWO staging buffers of the largest leaf regardless of
    model size — the reference's pinned-buffer-count bound with the
    count fixed at the double-buffer minimum.
    """

    def __init__(self, nvme_path, aio_config=None, sub_dir=None,
                 durable=False):
        """``sub_dir``/``durable``: by default the swap files are
        pid-scoped SCRATCH (reclaimed on GC/exit). A durable tier (the
        ZeRO-Infinity at-rest files, runtime/zero/infinity.py) passes a
        stable sub_dir and durable=True: files survive the process and
        carry a meta.json sidecar so a fresh process can restore."""
        self.dir = os.path.join(
            nvme_path, sub_dir or f"param_swap_{os.getpid()}")
        os.makedirs(self.dir, exist_ok=True)
        self.handle = _make_aio_handle(aio_config)
        self.meta = {}            # leaf idx -> (shape, numpy dtype)
        self._staging = [None, None]
        self._durable = durable
        if not durable:
            self._finalizer = weakref.finalize(
                self, shutil.rmtree, self.dir, ignore_errors=True)

    def _path(self, i):
        return os.path.join(self.dir, f"param_{i}.swp")

    def _meta_path(self):
        return os.path.join(self.dir, "meta.json")

    def save_meta(self):
        import json
        with open(self._meta_path(), "w") as f:
            json.dump({str(i): [list(s), str(np.dtype(d))]
                       for i, (s, d) in self.meta.items()}, f)

    def load_meta(self):
        """Restore leaf metadata written by a previous process's
        write_all (durable tiers only)."""
        import json
        with open(self._meta_path()) as f:
            raw = json.load(f)
        self.meta = {int(i): (tuple(s), np.dtype(d))
                     for i, (s, d) in raw.items()}
        return self.meta

    def _stage(self, i, nbytes):
        buf = self._staging[i % 2]
        if buf is None or buf.nbytes < nbytes:
            self._staging[i % 2] = buf = np.empty(nbytes, np.uint8)
        return buf[:nbytes]

    @staticmethod
    def _as_bytes(arr):
        return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)

    def write_all(self, leaves):
        """Initial population / re-park after checkpoint load: every leaf
        (device or host) → its file. Sync writes; called off the step
        path."""
        for i, leaf in enumerate(leaves):
            arr = np.ascontiguousarray(np.asarray(leaf))
            self.meta[i] = (arr.shape, arr.dtype)
            self.handle.sync_pwrite(self._as_bytes(arr), self._path(i))
        if self._durable:
            self.save_meta()

    def swap_in_device(self, shardings):
        """disk → device params; returns the list of device leaves."""
        import jax
        n = len(self.meta)
        outs = [None] * n
        fds = [None] * n

        def start_read(i):
            shape, dtype = self.meta[i]
            nbytes = int(np.prod(shape or (1,))) * dtype.itemsize
            buf = self._stage(i, nbytes)
            fds[i] = self.handle.open(self._path(i), False)
            self.handle.async_pread(buf, fds[i])
            return buf

        # CPU device_put aliases host memory — a reused staging buffer
        # would corrupt the "device" params. Decide from the TARGET
        # devices (an engine may run a CPU mesh under a TPU default)
        aliases_host = n > 0 and \
            shardings[0].mesh.devices.flat[0].platform == "cpu"
        pending_buf = start_read(0) if n else None
        for i in range(n):
            buf = pending_buf
            self.handle.wait()
            self.handle.close(fds[i])
            shape, dtype = self.meta[i]
            arr = buf[:int(np.prod(shape or (1,))) * dtype.itemsize] \
                .view(dtype).reshape(shape)
            host_arr = np.array(arr, copy=True) if aliases_host else arr
            outs[i] = jax.device_put(host_arr, shardings[i])
            if i + 1 < n:
                # the next read lands in buffer (i+1)%2 — leaf i-1's async
                # h2d from that same buffer must be complete first
                if i >= 1:
                    outs[i - 1].block_until_ready()
                pending_buf = start_read(i + 1)
        for o in outs:
            o.block_until_ready()
        return outs

    def swap_out_device(self, leaves):
        """device params → disk; frees nothing itself (callers delete the
        device arrays after). d2h transfers for all leaves start up front
        so later copies overlap earlier writes."""
        for leaf in leaves:
            if hasattr(leaf, "copy_to_host_async"):
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass
        for i, leaf in enumerate(leaves):
            arr = np.ascontiguousarray(np.asarray(leaf))
            self.meta[i] = (arr.shape, arr.dtype)
            self.handle.sync_pwrite(self._as_bytes(arr), self._path(i))

    def release(self):
        shutil.rmtree(self.dir, ignore_errors=True)


class OptimizerStateSwapper:
    """NVMe-resident Adam moments (the ZeRO-Infinity optimizer tier —
    reference optimizer_utils.py:118). Reads are double-buffered on a
    DEDICATED aio handle (the reference's PipelinedOptimizerSwapper
    overlap, pipelined_optimizer_swapper.py:60): ``prefetch(next_leaf)``
    starts the async read of the next leaf's moments while the caller
    computes on the current one; writes stay on the main handle. Staging
    buffers come from a contiguous arena (_StagingArena) instead of
    per-call numpy churn."""

    FIELDS = ("exp_avg", "exp_avg_sq")

    def __init__(self, nvme_path, aio_config=None):
        self.swapper = TensorSwapper(nvme_path, aio_config, "optimizer_swap")
        self.shapes = {}
        self._pf_handle = _make_aio_handle(aio_config)
        self._pf = None  # (leaf_id, [bufs], [fds], [tids])
        self._arena = _StagingArena()
        self._consumed = {}  # leaf_id -> [tids] handed out by fetch()

    def init_state(self, leaf_id, shape):
        self.shapes[leaf_id] = tuple(shape)
        zeros = np.zeros(shape, np.float32)
        for field in self.FIELDS:
            self.swapper.swap_out(f"{leaf_id}.{field}", zeros)

    def _drain_prefetch(self):
        if self._pf is None:
            return None
        leaf_id, bufs, fds, tids = self._pf
        self._pf = None
        try:
            self._pf_handle.wait()
        finally:
            for fd in fds:
                self._pf_handle.close(fd)
        return leaf_id, bufs, tids

    def _discard_prefetch(self):
        drained = self._drain_prefetch()
        if drained is not None:
            for tid in drained[2]:
                self._arena.give(tid)

    def _release_consumed(self, leaf_id):
        for tid in self._consumed.pop(leaf_id, ()):
            self._arena.give(tid)

    def prefetch(self, leaf_id):
        """Start the async read of ``leaf_id``'s moments; the matching
        fetch() consumes them without blocking on the disk."""
        if self._pf is not None and self._pf[0] == leaf_id:
            return
        self._discard_prefetch()
        shape = self.shapes[leaf_id]
        bufs, fds, tids = [], [], []
        for field in self.FIELDS:
            tid, buf = self._arena.take(shape)
            fd = self._pf_handle.open(
                self.swapper._path(f"{leaf_id}.{field}"), False)
            self._pf_handle.async_pread(buf, fd)
            bufs.append(buf)
            fds.append(fd)
            tids.append(tid)
        self._pf = (leaf_id, bufs, fds, tids)

    def fetch(self, leaf_id):
        # a re-fetch without an intervening store (e.g. state_dict() walks
        # every leaf read-only) must not orphan the previous staging slots
        self._release_consumed(leaf_id)
        if self._pf is not None and self._pf[0] == leaf_id:
            _, bufs, tids = self._drain_prefetch()
            self._consumed[leaf_id] = tids
            return bufs
        self._discard_prefetch()
        shape = self.shapes[leaf_id]
        out, tids = [], []
        for field in self.FIELDS:
            tid, buf = self._arena.take(shape)
            self.swapper.swap_in(f"{leaf_id}.{field}", buf)
            out.append(buf)
            tids.append(tid)
        self._consumed[leaf_id] = tids
        return out

    def store(self, leaf_id, exp_avg, exp_avg_sq):
        self.swapper.swap_out(f"{leaf_id}.exp_avg", exp_avg)
        self.swapper.swap_out(f"{leaf_id}.exp_avg_sq", exp_avg_sq)
        # the fetched staging views are dead once the new moments hit disk
        self._release_consumed(leaf_id)

    def release(self):
        try:
            self._discard_prefetch()
        except Exception:
            pass
        for leaf in list(self._consumed):
            self._release_consumed(leaf)
        self.swapper.release()
