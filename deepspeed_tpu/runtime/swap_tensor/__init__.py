from deepspeed_tpu.runtime.swap_tensor.swapper import (
    TensorSwapper,
    OptimizerStateSwapper,
    PartitionedParamSwapper,
    sweep_stale_pid_dirs,
)
