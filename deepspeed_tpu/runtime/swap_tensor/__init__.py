from deepspeed_tpu.runtime.swap_tensor.swapper import (
    TensorSwapper,
    OptimizerStateSwapper,
    PartitionedParamSwapper,
)
