"""State-dict factory — reference runtime/state_dict_factory.py:14
(`SDLoaderFactory`, `MegatronSDLoader`, `WeightQuantization`): loading
checkpoints across a CHANGED tensor-parallel degree by merging or splitting
per-mp-rank shard files, with optional weight quantization on load.

TPU context: this repo's own checkpoints store the full logical tree
(runtime/checkpointing.py) because GSPMD re-shards on restore — TP resize is
free. The factory exists for Megatron-STYLE checkpoints: one file per
mp_rank, each holding that rank's slice of every TP-sharded weight (the
format produced by torch Megatron exports and by `save_tp_sharded` below).
Merge/split axes come from a PartitionSpec tree (models/sharding.py /
ops/transformer/inference.py conventions) or name heuristics, with the
fused-QKV block layout handled specially like the reference's
merge_query_key_value/split_query_key_value (state_dict_factory.py:331-420).
"""

import os
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.mesh import MODEL_AXIS
from deepspeed_tpu.runtime.checkpointing import load_tree, save_tree
from deepspeed_tpu.utils.logging import logger

AUTO_TP_SIZE = 0


def _leaf_tp_axis(path_names, shape):
    """Which dim of this leaf is TP-sharded, or None. Mirrors the spec rules
    of models/sharding.py / inference_tp_specs: column-parallel producers
    shard the last dim, row-parallel consumers shard the first, embeddings
    shard the vocab dim."""
    names = [n.lower() for n in path_names]
    last = names[-1] if names else ""
    joined = "/".join(names)
    if last in ("bias", "scale") and len(shape) == 1:
        col = any(t in joined for t in
                  ("attn_qkvw", "c_attn", "query_key_value", "inter_w",
                   "c_fc", "dense_h_to_4h"))
        return 0 if col else None
    if len(shape) < 2:
        return None
    if any(t in joined for t in ("attn_qkvw", "c_attn", "query_key_value",
                                 "inter_w", "c_fc", "dense_h_to_4h")):
        return len(shape) - 1               # column parallel
    if any(t in joined for t in ("attn_ow", "c_proj", "output_w",
                                 "dense_4h_to_h")):
        return len(shape) - 2               # row parallel
    if any(t in joined for t in ("wte", "word_embeddings", "lm_head")):
        return 0                            # vocab parallel
    return None


def _is_qkv(path_names):
    joined = "/".join(n.lower() for n in path_names)
    return any(t in joined for t in ("attn_qkvw", "c_attn",
                                     "query_key_value"))


def _spec_tp_axis(spec):
    if spec is None:
        return None
    for i, ax in enumerate(spec):
        axes = ax if isinstance(ax, (tuple, list)) else (ax,)
        if MODEL_AXIS in axes:
            return i
    return None


def _merge_qkv(shards, axis):
    """Fused-QKV merge: each shard's qkv dim is [q_i; k_i; v_i] — concat
    per-component then re-fuse (reference merge_query_key_value)."""
    parts = [np.split(s, 3, axis=axis) for s in shards]
    return np.concatenate(
        [np.concatenate([p[c] for p in parts], axis=axis)
         for c in range(3)], axis=axis)


def _split_qkv(full, ratio, rank_in_group, axis):
    q, k, v = np.split(full, 3, axis=axis)
    picks = [np.array_split(c, ratio, axis=axis)[rank_in_group]
             for c in (q, k, v)]
    return np.concatenate(picks, axis=axis)


class WeightQuantization:
    """Quantize weights at load time (reference state_dict_factory.py:32 /
    module WeightQuantization): group-wise symmetric fake quant of 2-D
    weights; `quantize_packed` via ops.quantizer for int8 storage."""

    def __init__(self, bits=8, groups=64, mlp_extra_grouping=False):
        self.bits = bits
        self.groups = groups
        self.mlp_extra_grouping = mlp_extra_grouping

    def _groups_for(self, path_names):
        joined = "/".join(n.lower() for n in path_names)
        if self.mlp_extra_grouping and any(
                t in joined for t in ("inter_w", "output_w", "c_fc",
                                      "c_proj", "dense_h_to_4h",
                                      "dense_4h_to_h")):
            return self.groups * 2     # reference doubles MLP groups
        return self.groups

    def quantize_tree(self, params):
        from deepspeed_tpu.ops.quantizer import quantize_jnp

        def leaf(path, x):
            arr = np.asarray(x)
            if arr.ndim != 2 or not np.issubdtype(arr.dtype, np.floating):
                return x
            g = self._groups_for([str(getattr(k, "key", k)) for k in path])
            if arr.size % g != 0:
                g = 1
            return np.asarray(quantize_jnp(jnp.asarray(arr), bits=self.bits,
                                           groups=g, sym=True))
        return jax.tree_util.tree_map_with_path(leaf, params)


class SDLoaderBase:
    def __init__(self, ckpt_list: Sequence[str], specs=None):
        self.ckpt_list = list(ckpt_list)
        self.specs = specs

    def _tp_axis(self, path_names, leaf_shape, spec):
        ax = _spec_tp_axis(spec)
        if ax is not None:
            return ax
        return _leaf_tp_axis(path_names, leaf_shape)

    def load(self, mp_world_size: int, mp_rank: int,
             quantize: bool = False, quantize_bits: int = 8,
             quantize_groups: int = 64, mlp_extra_grouping: bool = False):
        """Return this mp_rank's param tree at the NEW mp_world_size
        (reference SDLoaderBase.load, state_dict_factory.py:73-130:
        same-degree passthrough, merge when shrinking, split when growing)."""
        src = len(self.ckpt_list)
        if mp_world_size == src:
            params = self._load_shard(self.ckpt_list[mp_rank])
        elif mp_world_size < src:
            assert src % mp_world_size == 0, (src, mp_world_size)
            ratio = src // mp_world_size
            group = self.ckpt_list[mp_rank * ratio:(mp_rank + 1) * ratio]
            params = self._merge_shards([self._load_shard(p) for p in group])
        else:
            assert mp_world_size % src == 0, (src, mp_world_size)
            ratio = mp_world_size // src
            params = self._split_shard(
                self._load_shard(self.ckpt_list[mp_rank // ratio]),
                ratio, mp_rank % ratio)
        if quantize:
            wq = WeightQuantization(quantize_bits, quantize_groups,
                                    mlp_extra_grouping)
            params = wq.quantize_tree(params)
        return params

    def _load_shard(self, path):
        tree = load_tree(path)
        return tree.get("params", tree)

    def _map2(self, fn, trees):
        """tree_map_with_path over parallel trees."""
        spec_tree = self.specs

        def walk(path, *leaves):
            names = [str(getattr(k, "key", k)) for k in path]
            spec = None
            if spec_tree is not None:
                node = spec_tree
                try:
                    for n in names:
                        node = node[n]
                    spec = node
                except (KeyError, TypeError):
                    spec = None
            return fn(names, spec, *leaves)
        return jax.tree_util.tree_map_with_path(walk, *trees)

    def _merge_shards(self, shards):
        def merge(names, spec, *leaves):
            arrs = [np.asarray(l) for l in leaves]
            ax = self._tp_axis(names, arrs[0].shape, spec)
            if ax is None:
                return arrs[0]
            if _is_qkv(names):
                return _merge_qkv(arrs, ax)
            return np.concatenate(arrs, axis=ax)
        return self._map2(merge, shards)

    def _split_shard(self, full, ratio, rank_in_group):
        def split(names, spec, leaf):
            arr = np.asarray(leaf)
            ax = self._tp_axis(names, arr.shape, spec)
            if ax is None:
                return arr
            if _is_qkv(names):
                return _split_qkv(arr, ratio, rank_in_group, ax)
            return np.array_split(arr, ratio, axis=ax)[rank_in_group]
        return self._map2(split, [full])


class MegatronSDLoader(SDLoaderBase):
    """Megatron layout loader (reference state_dict_factory.py:272): the
    name heuristics above already encode Megatron's column/row/vocab
    parallel split and fused-QKV layout."""


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file):
        import json
        with open(json_file) as f:
            data = json.load(f)
        return SDLoaderFactory.get_sd_loader(
            data["checkpoints"], data.get("type", "Megatron"))

    @staticmethod
    def get_sd_loader(ckpt_list, sd_type="Megatron", specs=None):
        if sd_type.lower() == "megatron":
            return MegatronSDLoader(ckpt_list, specs=specs)
        return SDLoaderBase(ckpt_list, specs=specs)


def save_tp_sharded(params, out_dir: str, mp_world_size: int, specs=None,
                    prefix="mp_rank"):
    """Export a full logical tree as Megatron-style per-mp-rank shard files
    — the inverse of SDLoaderBase.load, used for interop and tested as the
    roundtrip (reference pipeline writes these via engine.py:1524-1551
    naming)."""
    os.makedirs(out_dir, exist_ok=True)
    loader = SDLoaderBase([None], specs=specs)
    paths = []
    for r in range(mp_world_size):
        shard = loader._split_shard(params, mp_world_size, r) \
            if mp_world_size > 1 else jax.tree_util.tree_map(np.asarray,
                                                             params)
        path = os.path.join(out_dir, f"{prefix}_{r:02d}_model_states.npz")
        save_tree(path, {"params": shard})
        paths.append(path)
    return paths
