"""Row-compressed sparse gradients — reference runtime/csr_tensor.py:11
`CSRTensor` and the engine's sparse allreduce (engine.py:195-202,1444-1515).

The reference compresses embedding gradients to (row indices, dense rows)
before the data-parallel allreduce: each rank touches only the vocabulary
rows present in its local batch, so exchanging compressed rows beats
allreducing the full [V, E] matrix.

TPU shape: XLA needs static shapes, so compression selects up to a fixed
`max_rows` budget of touched rows (sized from batch·seq, exact when every
batch touches ≤ max_rows distinct ids). The collective is an `all_gather` of
(indices, rows) over the data axis inside `shard_map`, followed by a
scatter-add — the all-gather rides ICI, and the scatter-add lands on the
owning shard under GSPMD. With dense row-occupancy the engine's default
psum path wins; this is the opt-in for large-vocab embedding layers, exactly
the trade the reference makes (sparse_gradients_enabled, engine.py:195).
"""

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import shard_map


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CSRTensor:
    """Row-compressed tensor: `indices[i]` is the dense row of `values[i]`.
    Padding slots carry index == dense_shape[0] (dropped on scatter).
    Mirrors the reference CSRTensor surface (runtime/csr_tensor.py:11):
    sparse/dense construction, addition, to_dense."""
    indices: jax.Array            # [max_rows] int32
    values: jax.Array             # [max_rows, width]
    dense_shape: Tuple[int, int]  # static

    def tree_flatten(self):
        return (self.indices, self.values), self.dense_shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @classmethod
    def from_dense(cls, dense, max_rows: int) -> "CSRTensor":
        """Compress the nonzero rows of [V, E] into a static [max_rows, E]
        buffer. If more than max_rows rows are nonzero, the largest-magnitude
        rows win (lossy overflow is asserted against in sparse_all_reduce
        by budget sizing)."""
        V, E = dense.shape
        row_mag = jnp.sum(jnp.abs(dense), axis=1)
        # top-k by magnitude, nonzero rows first
        _, idx = jax.lax.top_k(row_mag, min(max_rows, V))
        got = row_mag[idx] > 0
        idx = jnp.where(got, idx, V)          # pad slot → out-of-range
        vals = jnp.where(got[:, None],
                         dense[jnp.clip(idx, 0, V - 1)], 0)
        if idx.shape[0] < max_rows:           # V < max_rows: pad up
            pad = max_rows - idx.shape[0]
            idx = jnp.concatenate([idx, jnp.full((pad,), V, idx.dtype)])
            vals = jnp.concatenate([vals, jnp.zeros((pad, E), vals.dtype)])
        return cls(idx.astype(jnp.int32), vals, (V, E))

    def to_dense(self) -> jax.Array:
        V, E = self.dense_shape
        out = jnp.zeros((V, E), self.values.dtype)
        return out.at[self.indices].add(self.values, mode="drop")

    def add(self, other: "CSRTensor") -> "CSRTensor":
        """Concatenating row lists implements addition (duplicates resolve in
        to_dense's scatter-add), like reference CSRTensor.add."""
        assert self.dense_shape == other.dense_shape
        return CSRTensor(jnp.concatenate([self.indices, other.indices]),
                         jnp.concatenate([self.values, other.values]),
                         self.dense_shape)

    @property
    def nnz_rows(self):
        return jnp.sum(self.indices < self.dense_shape[0])


def sparse_all_reduce(dense_grad, mesh, axis: str, max_rows: int):
    """Data-parallel sum of a row-sparse gradient via compressed exchange:
    per-rank compress → all_gather(idx, rows) over `axis` → scatter-add.
    Numerically equals psum when each rank touches ≤ max_rows rows
    (the engine sparse path, reference engine.py:1444-1515).

    `dense_grad` carries the per-rank gradient stacked over the axis — i.e.
    call this inside shard_map/pjit where `dense_grad` is the local [V, E]
    shard-view; here we provide the host-level entry taking a global array
    sharded over `axis` on its leading (batch-of-grads) dim is NOT the
    layout — instead pass the per-rank grads as [world, V, E]."""
    world = mesh.shape[axis]

    def local_reduce(g):          # g: [1, V, E] local block
        g = g[0]
        csr = CSRTensor.from_dense(g, max_rows)
        all_idx = jax.lax.all_gather(csr.indices, axis)    # [W, max_rows]
        all_val = jax.lax.all_gather(csr.values, axis)     # [W, max_rows, E]
        V, E = csr.dense_shape
        out = jnp.zeros((V, E), g.dtype)
        out = out.at[all_idx.reshape(-1)].add(
            all_val.reshape(-1, E), mode="drop")
        return out[None]

    fn = shard_map(local_reduce, mesh=mesh,
                   in_specs=P(axis, None, None),
                   out_specs=P(axis, None, None))
    summed = fn(dense_grad)
    # every rank computed the same full sum; return rank-0's copy
    return summed[0]
