"""1-bit Adam — rebuild of deepspeed/runtime/fp16/onebit/adam.py:14.

Two phases (reference :146-189):
  warmup  (step < freeze_step): exact Adam; both moments update.
  compressed (step >= freeze_step): the variance is FROZEN; the momentum is
  communicated 1-bit sign-compressed with error feedback:

      c      = sign(m + e) * mean(|m + e|)     (per-tensor scale)
      e_new  = (m + e) - c
      update = c / (sqrt(v_frozen) + eps)

The reference runs the sign-compress + alltoall + allgather over
NCCL/MPI with cupy bit packing (runtime/comm/nccl.py:47-186). Here the
compression state machine lives in the optimizer (identical math); the ICI
all_to_all with packed signs is provided by
deepspeed_tpu/parallel/compression.py for the multi-host path, and the
error-feedback tensors shard with the rest of the optimizer state under
ZeRO (they are param-like).
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, tree_zeros_like


@dataclasses.dataclass
class OnebitAdam(TpuOptimizer):
    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    freeze_step: int = 100000
    cuda_aware: bool = False   # parity field; meaningless on TPU
    comm_backend_name: str = "ici"

    param_like_state_fields = ("exp_avg", "exp_avg_sq", "worker_error")
    # engine switches to the shard_map compressed train step when the data
    # axis is >1 (the reference's pipeline_enable_backward_allreduce=False
    # + backend.compressed_allreduce wiring, onebit/adam.py:92-104)
    supports_compressed_comm = True

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
            "worker_error": tree_zeros_like(params, jnp.float32),
        }

    def init_compressed(self, params, dp_size, comm=None):
        """Optimizer state for the distributed compressed path: moments are
        replicated (synchronized by the collective); the two error-feedback
        trees are PER-DEVICE, stored with a leading [dp] axis the engine
        shards over the data axis. With ``comm`` (an
        overlap.HierarchyPlan), the errors are per-BUCKET lists shaped
        for the hierarchical exchange instead of per-leaf trees."""
        if comm is not None:
            from deepspeed_tpu.parallel import overlap
            we, se = overlap.hierarchical_error_states(params, comm)
        else:
            from deepspeed_tpu.parallel import compression as comp
            we, se = comp.init_error_states(params, dp_size)
        bump = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jnp.zeros((dp_size,) + x.shape, x.dtype), t)
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
            "worker_error": bump(we),
            "server_error": bump(se),
        }

    def step_local(self, params, grads, state, lr, axis_name, clip=None,
                   comm=None):
        """Distributed step, called inside shard_map over ``axis_name`` with
        UNREDUCED per-device grads; error-feedback leaves arrive without
        their leading dp axis (the engine strips/restores it).

        warmup: exact DP — grads pmean'd, both moments update, optional
        global-norm clip. compressed: momentum updates from LOCAL grads and
        is synchronized by the 1-bit collective; variance frozen.

        ``comm`` (overlap.HierarchyPlan) switches both phases to the
        link-aware bucketed exchange (ISSUE 10): ``axis_name`` is then
        the (inter, intra) axis tuple, warmup means grads through the
        two-level uncompressed bucket stream, and the compressed phase
        runs the per-bucket policy — only slow-axis hops carry sign
        bits. Error-feedback state is per-bucket lists there (see
        overlap.hierarchical_error_states)."""
        from deepspeed_tpu.parallel.compression import tree_compressed_allreduce
        from deepspeed_tpu.parallel import overlap
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        frozen = count > self.freeze_step
        tm = jax.tree_util.tree_map

        def warmup(grads, m, v, we, se):
            if comm is not None:
                # cast BEFORE the bucket stream: _unpack_bucket restores
                # leaf dtype, so fp32-in keeps the mean at fp32 (no extra
                # bf16 rounding vs the flat pmean path)
                g = overlap.bucketed_hierarchical_mean(
                    tm(lambda x: x.astype(jnp.float32), grads), comm)
            else:
                g = tm(lambda x: jax.lax.pmean(x.astype(jnp.float32),
                                               axis_name), grads)
            if clip:
                sq = sum(jnp.sum(jnp.square(l))
                         for l in jax.tree_util.tree_leaves(g))
                coef = jnp.minimum(1.0, clip / (jnp.sqrt(sq) + 1e-6))
                g = tm(lambda x: x * coef, g)
            m_new = tm(lambda mm, gg: beta1 * mm + (1 - beta1) * gg, m, g)
            v_new = tm(lambda vv, gg: beta2 * vv + (1 - beta2) * gg * gg, v, g)
            return m_new, m_new, v_new, we, se

        def compressed(grads, m, v, we, se):
            m_loc = tm(lambda mm, gg: beta1 * mm
                       + (1 - beta1) * gg.astype(jnp.float32), m, grads)
            if comm is not None:
                m_sync, we2, se2 = \
                    overlap.bucketed_hierarchical_compressed_allreduce(
                        m_loc, we, se, comm)
            else:
                m_sync, we2, se2 = tree_compressed_allreduce(
                    m_loc, we, se, axis_name)
            return m_sync, m_sync, v, we2, se2

        m_eff, m_new, v_new, we2, se2 = jax.lax.cond(
            frozen, compressed, warmup,
            grads, state["exp_avg"], state["exp_avg_sq"],
            state["worker_error"], state["server_error"])

        def apply_leaf(p, m, v):
            p32 = p.astype(jnp.float32)
            update = m / (jnp.sqrt(v) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            return (p32 - lr * update).astype(p.dtype)

        new_params = tm(apply_leaf, params, m_eff, v_new)
        return new_params, {"step": count, "exp_avg": m_new,
                            "exp_avg_sq": v_new, "worker_error": we2,
                            "server_error": se2}

    def step(self, params, grads, state, lr=None):
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        frozen = count > self.freeze_step

        def update_leaf(p, g, m, v, e):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g32
            # variance freezes at the compression boundary (reference :170)
            v_new = jnp.where(frozen, v, beta2 * v + (1.0 - beta2) * g32 * g32)

            # compressed path: sign + scale with error feedback
            corrected = m_new + e
            scale = jnp.mean(jnp.abs(corrected))
            compressed = jnp.sign(corrected) * scale
            e_new = jnp.where(frozen, corrected - compressed, e)
            m_eff = jnp.where(frozen, compressed, m_new)

            update = m_eff / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            p_new = p32 - lr * update
            return p_new.astype(p.dtype), m_new, v_new, e_new

        flat = jax.tree_util.tree_map(update_leaf, params, grads,
                                      state["exp_avg"], state["exp_avg_sq"],
                                      state["worker_error"])
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t: t[i], flat, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"step": count, "exp_avg": pick(1),
                         "exp_avg_sq": pick(2), "worker_error": pick(3)}
