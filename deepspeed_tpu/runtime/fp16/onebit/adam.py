"""1-bit Adam — rebuild of deepspeed/runtime/fp16/onebit/adam.py:14.

Two phases (reference :146-189):
  warmup  (step < freeze_step): exact Adam; both moments update.
  compressed (step >= freeze_step): the variance is FROZEN; the momentum is
  communicated 1-bit sign-compressed with error feedback:

      c      = sign(m + e) * mean(|m + e|)     (per-tensor scale)
      e_new  = (m + e) - c
      update = c / (sqrt(v_frozen) + eps)

The reference runs the sign-compress + alltoall + allgather over
NCCL/MPI with cupy bit packing (runtime/comm/nccl.py:47-186). Here the
compression state machine lives in the optimizer (identical math); the ICI
all_to_all with packed signs is provided by
deepspeed_tpu/parallel/compression.py for the multi-host path, and the
error-feedback tensors shard with the rest of the optimizer state under
ZeRO (they are param-like).
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, tree_zeros_like


@dataclasses.dataclass
class OnebitAdam(TpuOptimizer):
    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    freeze_step: int = 100000
    cuda_aware: bool = False   # parity field; meaningless on TPU
    comm_backend_name: str = "ici"

    param_like_state_fields = ("exp_avg", "exp_avg_sq", "worker_error")

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
            "worker_error": tree_zeros_like(params, jnp.float32),
        }

    def step(self, params, grads, state, lr=None):
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        frozen = count > self.freeze_step

        def update_leaf(p, g, m, v, e):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g32
            # variance freezes at the compression boundary (reference :170)
            v_new = jnp.where(frozen, v, beta2 * v + (1.0 - beta2) * g32 * g32)

            # compressed path: sign + scale with error feedback
            corrected = m_new + e
            scale = jnp.mean(jnp.abs(corrected))
            compressed = jnp.sign(corrected) * scale
            e_new = jnp.where(frozen, corrected - compressed, e)
            m_eff = jnp.where(frozen, compressed, m_new)

            update = m_eff / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            p_new = p32 - lr * update
            return p_new.astype(p.dtype), m_new, v_new, e_new

        flat = jax.tree_util.tree_map(update_leaf, params, grads,
                                      state["exp_avg"], state["exp_avg_sq"],
                                      state["worker_error"])
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t: t[i], flat, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"step": count, "exp_avg": pick(1),
                         "exp_avg_sq": pick(2), "worker_error": pick(3)}
