"""1-bit LAMB — rebuild of deepspeed/runtime/fp16/onebit/lamb.py:11.

Warmup phase (step < freeze_step): exact LAMB, while recording the running
ratio of ||update||/||momentum|| ("scaling coefficient") per tensor, which
the compressed phase reuses — the reference freezes both the variance and
the lamb coefficient bounds at freeze_step (:175-210, 1-bit LAMB paper
arXiv:2104.06069).

Compressed phase: momentum sign-compressed with error feedback (as 1-bit
Adam); the frozen per-tensor scaling coefficient replaces a fresh trust
ratio (which would need the uncompressed update norm).
"""

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TpuOptimizer, tree_zeros_like


def _tree_scalar_like(params, value):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(value, jnp.float32), params)


@dataclasses.dataclass
class OnebitLamb(TpuOptimizer):
    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    freeze_step: int = 100000
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    coeff_beta: float = 0.9

    param_like_state_fields = ("exp_avg", "exp_avg_sq", "worker_error")
    supports_compressed_comm = True

    def init(self, params):
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
            "worker_error": tree_zeros_like(params, jnp.float32),
            "lamb_coeff": _tree_scalar_like(params, 1.0),
        }

    def init_compressed(self, params, dp_size, comm=None):
        """State for the distributed compressed path (see OnebitAdam
        .init_compressed): error-feedback trees per-device with a leading
        [dp] axis; moments and coefficients replicated. ``comm`` (an
        overlap.HierarchyPlan) switches the errors to per-bucket lists
        for the hierarchical exchange."""
        if comm is not None:
            from deepspeed_tpu.parallel import overlap
            we, se = overlap.hierarchical_error_states(params, comm)
        else:
            from deepspeed_tpu.parallel import compression as comp
            we, se = comp.init_error_states(params, dp_size)
        bump = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jnp.zeros((dp_size,) + x.shape, x.dtype), t)
        return {
            "step": jnp.zeros((), jnp.int32),
            "exp_avg": tree_zeros_like(params, jnp.float32),
            "exp_avg_sq": tree_zeros_like(params, jnp.float32),
            "worker_error": bump(we),
            "server_error": bump(se),
            "lamb_coeff": _tree_scalar_like(params, 1.0),
        }

    def step_local(self, params, grads, state, lr, axis_name, clip=None,
                   comm=None):
        """Distributed step inside shard_map over ``axis_name`` (unreduced
        per-device grads). Warmup = exact LAMB on pmean'd grads, recording
        the running scaling coefficient; compressed = 1-bit momentum
        collective + frozen coefficient (the reference's two-phase design,
        arXiv:2104.06069). ``comm`` switches both phases to the
        hierarchical bucketed exchange (see OnebitAdam.step_local)."""
        from deepspeed_tpu.parallel.compression import tree_compressed_allreduce
        from deepspeed_tpu.parallel import overlap
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        frozen = count > self.freeze_step
        tm = jax.tree_util.tree_map

        def warmup(grads, m, v, we, se):
            if comm is not None:
                # fp32-in so _unpack_bucket's leaf-dtype restore does not
                # re-round the mean (see OnebitAdam.step_local)
                g = overlap.bucketed_hierarchical_mean(
                    tm(lambda x: x.astype(jnp.float32), grads), comm)
            else:
                g = tm(lambda x: jax.lax.pmean(x.astype(jnp.float32),
                                               axis_name), grads)
            if clip:
                sq = sum(jnp.sum(jnp.square(l))
                         for l in jax.tree_util.tree_leaves(g))
                coef = jnp.minimum(1.0, clip / (jnp.sqrt(sq) + 1e-6))
                g = tm(lambda x: x * coef, g)
            m_new = tm(lambda mm, gg: beta1 * mm + (1 - beta1) * gg, m, g)
            v_new = tm(lambda vv, gg: beta2 * vv + (1 - beta2) * gg * gg, v, g)
            return m_new, m_new, v_new, we, se

        def compressed(grads, m, v, we, se):
            m_loc = tm(lambda mm, gg: beta1 * mm
                       + (1 - beta1) * gg.astype(jnp.float32), m, grads)
            if comm is not None:
                m_sync, we2, se2 = \
                    overlap.bucketed_hierarchical_compressed_allreduce(
                        m_loc, we, se, comm)
            else:
                m_sync, we2, se2 = tree_compressed_allreduce(
                    m_loc, we, se, axis_name)
            return m_sync, m_sync, v, we2, se2

        m_eff, m_new, v_new, we2, se2 = jax.lax.cond(
            frozen, compressed, warmup,
            grads, state["exp_avg"], state["exp_avg_sq"],
            state["worker_error"], state["server_error"])

        def apply_leaf(p, m, v, coeff):
            p32 = p.astype(jnp.float32)
            update = m / (jnp.sqrt(v) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(update * update))
            fresh = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / jnp.maximum(u_norm, 1e-12),
                              jnp.float32(1.0))
            fresh = jnp.clip(fresh, self.min_coeff, self.max_coeff)
            coeff_new = jnp.where(
                frozen, coeff,
                self.coeff_beta * coeff + (1.0 - self.coeff_beta) * fresh)
            trust = jnp.where(frozen, coeff_new, fresh)
            return (p32 - lr * trust * update).astype(p.dtype), coeff_new

        applied = tm(apply_leaf, params, m_eff, v_new, state["lamb_coeff"])
        pick = lambda i: tm(  # noqa: E731
            lambda t: t[i], applied, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"step": count, "exp_avg": m_new,
                         "exp_avg_sq": v_new, "worker_error": we2,
                         "server_error": se2, "lamb_coeff": pick(1)}

    def step(self, params, grads, state, lr=None):
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        count = state["step"] + 1
        frozen = count > self.freeze_step

        def update_leaf(p, g, m, v, e, coeff):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g32
            v_new = jnp.where(frozen, v, beta2 * v + (1.0 - beta2) * g32 * g32)

            corrected = m_new + e
            scale = jnp.mean(jnp.abs(corrected))
            compressed = jnp.sign(corrected) * scale
            e_new = jnp.where(frozen, corrected - compressed, e)
            m_eff = jnp.where(frozen, compressed, m_new)

            update = m_eff / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32

            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            u_norm = jnp.sqrt(jnp.sum(update * update))
            fresh = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / jnp.maximum(u_norm, 1e-12),
                              jnp.float32(1.0))
            fresh = jnp.clip(fresh, self.min_coeff, self.max_coeff)
            # running estimate during warmup, frozen afterwards (:188)
            coeff_new = jnp.where(
                frozen, coeff,
                self.coeff_beta * coeff + (1.0 - self.coeff_beta) * fresh)
            trust = jnp.where(frozen, coeff_new, fresh)

            p_new = p32 - lr * trust * update
            return p_new.astype(p.dtype), m_new, v_new, e_new, coeff_new

        flat = jax.tree_util.tree_map(update_leaf, params, grads,
                                      state["exp_avg"], state["exp_avg_sq"],
                                      state["worker_error"], state["lamb_coeff"])
        pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
            lambda t: t[i], flat, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"step": count, "exp_avg": pick(1),
                         "exp_avg_sq": pick(2), "worker_error": pick(3),
                         "lamb_coeff": pick(4)}
