from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam
from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb
